"""Pallas fused depthwise kernel vs the XLA reference, in interpret mode
(CPU): forward exactness across kernel sizes/strides/activations, gradient
path through the custom VJP, and BN-fold algebra."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.ops import pallas_kernels as pk
from yet_another_mobilenet_series_tpu.ops.layers import BatchNorm, Conv2D


@pytest.mark.parametrize("k,stride,act", [
    (3, 1, "relu6"),
    (3, 2, "hswish"),
    (5, 1, "swish"),
    (7, 2, "relu"),
])
def test_fused_matches_reference(k, stride, act):
    rng = np.random.RandomState(0)
    n, h, w, c = 2, 12, 12, 16
    x = jnp.asarray(rng.normal(size=(n, h, w, c)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=(k, k, c)).astype(np.float32) * 0.2)
    scale = jnp.asarray(rng.uniform(0.5, 1.5, c).astype(np.float32))
    shift = jnp.asarray(rng.uniform(-0.3, 0.3, c).astype(np.float32))
    mask = jnp.ones(c).at[::3].set(0.0)

    y = pk.fused_depthwise_inference(x, wt, scale, shift, mask, stride, act, True)
    y_ref = pk._reference_fwd(x, wt, scale, shift, mask, stride=stride, act=act)
    assert y.shape == y_ref.shape
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("c", [160, 200])
def test_fused_channel_blocking_matches_reference(c):
    """Channels beyond _C_BLOCK split across grid steps — including a
    non-divisible count (200 = 128 + 72 with a padded tail block)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(size=(2, 9, 9, c)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=(3, 3, c)).astype(np.float32) * 0.2)
    scale = jnp.asarray(rng.uniform(0.5, 1.5, c).astype(np.float32))
    shift = jnp.asarray(rng.uniform(-0.3, 0.3, c).astype(np.float32))
    mask = jnp.ones(c).at[::5].set(0.0)
    for stride in (1, 2):
        y = pk.fused_depthwise_inference(x, wt, scale, shift, mask, stride, "hswish", True)
        y_ref = pk._reference_fwd(x, wt, scale, shift, mask, stride=stride, act="hswish")
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=1e-5, atol=1e-5)


def test_fused_equals_layer_pipeline():
    """Kernel == Conv2D(depthwise) -> BN(eval) -> act -> mask from ops/."""
    c, k = 8, 3
    conv = Conv2D(c, c, k, 1, groups=c)
    bn = BatchNorm(c)
    params = conv.init(jax.random.PRNGKey(0))
    bn_p, bn_s = bn.init()
    bn_p["gamma"] = jnp.asarray(np.random.RandomState(1).uniform(0.5, 1.5, c).astype(np.float32))
    bn_s = {"mean": jnp.asarray(np.random.RandomState(2).normal(size=c).astype(np.float32)),
            "var": jnp.asarray(np.random.RandomState(3).uniform(0.5, 2.0, c).astype(np.float32))}
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 10, 10, c))

    y_layers, _ = bn.apply(bn_p, bn_s, conv.apply(params, x), train=False)
    y_layers = jnp.clip(y_layers, 0, 6)

    scale, shift = pk.fold_bn(bn_p["gamma"], bn_p["beta"], bn_s["mean"], bn_s["var"], bn.eps)
    w3 = params["w"][:, :, 0, :]  # (k,k,1,C) HWIO -> (k,k,C)
    y_fused = pk.fused_depthwise_inference(x, w3, scale, shift, jnp.ones(c), 1, "relu6", True)
    np.testing.assert_allclose(np.asarray(y_fused), np.asarray(y_layers), rtol=1e-4, atol=1e-5)


def test_custom_vjp_gradients_match_reference():
    rng = np.random.RandomState(0)
    c = 8
    x = jnp.asarray(rng.normal(size=(2, 8, 8, c)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=(3, 3, c)).astype(np.float32) * 0.3)
    scale = jnp.ones(c)
    shift = jnp.zeros(c)
    mask = jnp.ones(c)

    def loss_fused(x, wt):
        return jnp.sum(pk.fused_depthwise_inference(x, wt, scale, shift, mask, 1, "hswish", True) ** 2)

    def loss_ref(x, wt):
        return jnp.sum(pk._reference_fwd(x, wt, scale, shift, mask, stride=1, act="hswish") ** 2)

    gx_f, gw_f = jax.grad(loss_fused, argnums=(0, 1))(x, wt)
    gx_r, gw_r = jax.grad(loss_ref, argnums=(0, 1))(x, wt)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r), rtol=1e-4, atol=1e-5)
