"""obs/ subsystem tests: registry semantics, span tracer (nesting, ring
eviction, Chrome-trace schema), stall watchdog (fires on an injected stall,
silent on a healthy loop), Logger integration (TF-less degrade, registry
snapshots in scalars rows), the fake-data train smoke (trace + snapshot
artifacts for steps_per_dispatch 1 and >1), and scripts/obs_report.py."""

import importlib.util
import json
import os
import time

import pytest

from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.obs.registry import MetricsRegistry, get_registry
from yet_another_mobilenet_series_tpu.obs.trace import SpanTracer
from yet_another_mobilenet_series_tpu.obs import trace as obs_trace
from yet_another_mobilenet_series_tpu.obs.watchdog import StallWatchdog
from yet_another_mobilenet_series_tpu.utils import logging as logging_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("a.hits").inc()
    reg.counter("a.hits").inc(2)
    reg.gauge("a.level").set(7.5)
    h = reg.histogram("a.wait")
    h.observe(1.0)
    h.observe(3.0)
    snap = reg.snapshot()
    assert snap["a.hits"] == 3.0
    assert snap["a.level"] == 7.5
    assert snap["a.wait.count"] == 2.0
    assert snap["a.wait.sum"] == 4.0
    assert snap["a.wait.mean"] == 2.0
    assert snap["a.wait.max"] == 3.0
    # get-or-create returns the SAME metric object
    assert reg.counter("a.hits") is reg.counter("a.hits")


def test_registry_type_conflict_and_negative_inc():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")
    with pytest.raises(ValueError, match="cannot decrease"):
        reg.counter("x").inc(-1)


def test_registry_gauge_callback_and_fault_isolation():
    reg = MetricsRegistry()
    src = {"v": 5}
    g = reg.gauge("pull")
    g.set_fn(lambda: src["v"])
    assert reg.snapshot()["pull"] == 5.0
    src["v"] = 9
    assert reg.snapshot()["pull"] == 9.0
    # a dying producer keeps the last good reading, never raises
    g.set_fn(lambda: 1 / 0)
    assert reg.snapshot()["pull"] == 9.0


def test_registry_reset():
    reg = MetricsRegistry()
    reg.counter("x").inc()
    reg.reset()
    assert reg.snapshot() == {}


# ---------------------------------------------------------------------------
# bucketed histograms: quantiles, min, concurrency, Prometheus exposition
# ---------------------------------------------------------------------------


def test_histogram_min_reported():
    """The satellite fix: vmin was tracked under the lock but never
    reported — it must reach summary(), snapshot(), and stay correct."""
    reg = MetricsRegistry()
    h = reg.histogram("t.wait")
    for v in (0.2, 0.005, 0.07):
        h.observe(v)
    s = h.summary()
    assert s["min"] == 0.005 and s["max"] == 0.2
    snap = reg.snapshot()
    assert snap["t.wait.min"] == 0.005
    # empty histogram reports zeros, never inf
    assert reg.histogram("t.empty").summary()["min"] == 0.0


def test_histogram_bucketed_quantiles_vs_sorted_reference():
    """Bucketed p50/p95/p99 must land within one bucket width of the exact
    sorted-sample quantile (the estimator interpolates inside the bucket
    that crosses the target rank)."""
    import numpy as np

    rng = np.random.RandomState(7)
    samples = np.exp(rng.uniform(np.log(2e-4), np.log(20.0), 4000))
    h = MetricsRegistry().histogram("t.lat")
    for v in samples:
        h.observe(float(v))
    for q in (0.5, 0.95, 0.99):
        ref = float(np.quantile(samples, q))
        est = h.quantile(q)
        # one bucket on the default quarter-decade ladder is a 10**0.25
        # (~1.78x) span: the estimate must stay inside the ref's bucket
        assert ref / (10 ** 0.25) <= est <= ref * (10 ** 0.25), (q, ref, est)
    # quantiles are monotone and clamped to the observed range
    s = h.summary()
    assert s["min"] <= s["p50"] <= s["p95"] <= s["p99"] <= s["max"]


def test_histogram_concurrent_observe_consistent():
    import threading

    h = MetricsRegistry().histogram("t.conc")
    n_threads, per_thread = 8, 500

    def worker(i):
        for j in range(per_thread):
            h.observe(1e-3 * (1 + (i * per_thread + j) % 97))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * per_thread
    assert sum(h.bucket_counts()) == h.count  # no lost bucket increments
    assert h.summary()["p50"] > 0


def test_histogram_custom_default_buckets():
    """set_default_buckets (the obs.histogram_buckets config knob) applies
    to histograms created AFTER the call; existing ladders are untouched."""
    reg = MetricsRegistry()
    before = reg.histogram("a")
    reg.set_default_buckets([0.1, 1.0, 10.0])
    after = reg.histogram("b")
    assert after.bounds == (0.1, 1.0, 10.0)
    assert before.bounds != after.bounds
    assert reg.histogram("a") is before  # get-or-create keeps the old ladder


def test_quantiles_from_counts_empty_and_zero_observations():
    """Edge cases the serving bench's delta math can hit: an all-zero count
    window (no observations between snapshots) and an empty-histogram
    summary must yield zeros, never a divide-by-zero or an inf clamp."""
    from yet_another_mobilenet_series_tpu.obs.registry import (
        DEFAULT_BUCKET_BOUNDS, quantiles_from_counts)

    counts = [0] * (len(DEFAULT_BUCKET_BOUNDS) + 1)
    assert quantiles_from_counts(DEFAULT_BUCKET_BOUNDS, counts, (0.5, 0.95, 0.99)) == [0.0, 0.0, 0.0]
    # vmin/vmax still at their empty sentinels (inf/-inf) must not leak out
    assert quantiles_from_counts(
        DEFAULT_BUCKET_BOUNDS, counts, (0.5,), vmin=float("inf"), vmax=float("-inf")) == [0.0]
    h = MetricsRegistry().histogram("t.never_observed")
    s = h.summary()
    assert s == {"count": 0.0, "sum": 0.0, "mean": 0.0, "min": 0.0, "max": 0.0,
                 "p50": 0.0, "p95": 0.0, "p99": 0.0}
    assert h.quantile(0.99) == 0.0


def test_render_prometheus_empty_histogram():
    """A histogram with no samples still renders a complete, finite family:
    zero cumulative buckets, zero sum/count, zero quantiles — a scraper must
    never see NaN/inf from a warmed-but-idle latency metric."""
    reg = MetricsRegistry()
    reg.histogram("serve.latency_seconds.batch", bounds=[0.01, 0.1])
    golden = "\n".join([
        '# TYPE serve_latency_seconds histogram',
        'serve_latency_seconds_bucket{class="batch",le="0.01"} 0',
        'serve_latency_seconds_bucket{class="batch",le="0.1"} 0',
        'serve_latency_seconds_bucket{class="batch",le="+Inf"} 0',
        'serve_latency_seconds_sum{class="batch"} 0',
        'serve_latency_seconds_count{class="batch"} 0',
        'serve_latency_seconds{class="batch",quantile="0.5"} 0',
        'serve_latency_seconds{class="batch",quantile="0.95"} 0',
        'serve_latency_seconds{class="batch",quantile="0.99"} 0',
    ]) + "\n"
    assert reg.render_prometheus() == golden
    for v in reg.snapshot().values():
        assert v == v and abs(v) != float("inf")  # finite, not NaN


def test_render_prometheus_golden():
    """Exposition golden: counter/gauge samples, a labeled per-class
    histogram with cumulative buckets + quantile lines, TYPE lines once per
    family — the exact text GET /metrics serves."""
    reg = MetricsRegistry()
    reg.counter("serve.requests").inc(5)
    reg.counter("serve.requests.interactive").inc(3)
    reg.gauge("serve.inflight").set(2)
    h = reg.histogram("serve.latency_seconds.interactive", bounds=[0.01, 0.1, 1.0])
    for v in (0.005, 0.05, 0.5):
        h.observe(v)
    golden = "\n".join([
        '# TYPE serve_inflight gauge',
        'serve_inflight 2',
        '# TYPE serve_latency_seconds histogram',
        'serve_latency_seconds_bucket{class="interactive",le="0.01"} 1',
        'serve_latency_seconds_bucket{class="interactive",le="0.1"} 2',
        'serve_latency_seconds_bucket{class="interactive",le="1"} 3',
        'serve_latency_seconds_bucket{class="interactive",le="+Inf"} 3',
        'serve_latency_seconds_sum{class="interactive"} 0.555',
        'serve_latency_seconds_count{class="interactive"} 3',
        'serve_latency_seconds{class="interactive",quantile="0.5"} 0.055',
        'serve_latency_seconds{class="interactive",quantile="0.95"} 0.44',
        'serve_latency_seconds{class="interactive",quantile="0.99"} 0.488',
        '# TYPE serve_requests counter',
        'serve_requests 5',
        'serve_requests{class="interactive"} 3',
    ]) + "\n"
    assert reg.render_prometheus() == golden


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def _x_events(tracer):
    return [e for e in tracer.to_chrome_trace()["traceEvents"] if e["ph"] == "X"]


def test_tracer_span_nesting_and_containment():
    tr = SpanTracer(ring_size=16)
    with tr.span("outer", "dispatch", steps=2):
        with tr.span("inner", "sync"):
            time.sleep(0.001)
    evts = _x_events(tr)
    # completion order: inner closes first
    assert [e["name"] for e in evts] == ["inner", "outer"]
    inner, outer = evts
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["args"] == {"steps": 2}


def test_tracer_ring_eviction():
    tr = SpanTracer(ring_size=4)
    for i in range(10):
        with tr.span(f"s{i}", "data"):
            pass
    evts = _x_events(tr)
    assert [e["name"] for e in evts] == ["s6", "s7", "s8", "s9"]


def test_tracer_chrome_trace_schema(tmp_path):
    tr = SpanTracer(ring_size=8)
    with tr.span("a", "data"):
        pass
    path = tr.write(str(tmp_path / "trace.json"))
    doc = json.loads(open(path).read())
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M")
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["cat"], str)


def test_tracer_disabled_is_noop():
    tr = SpanTracer(ring_size=8, enabled=False)
    s1 = tr.span("a", "data")
    s2 = tr.span("b", "sync")
    assert s1 is s2  # the shared null span: zero allocation on the hot path
    with s1:
        pass
    assert _x_events(tr) == []


def test_tracer_open_spans_readout():
    tr = SpanTracer(ring_size=8)
    with tr.span("outer", "dispatch"):
        with tr.span("inner", "data"):
            open_now = tr.open_spans()
            assert [s["name"] for s in open_now] == ["outer", "inner"]
            assert all(s["open_for_s"] >= 0 for s in open_now)
    assert tr.open_spans() == []


def test_tracer_misnested_exit_recovered_and_counted():
    """The satellite fix: an out-of-order exit must remove the span by
    identity (not leave it stuck in _open polluting every later hang
    report) and count obs.misnested_spans."""
    reg = get_registry()
    base = reg.snapshot().get("obs.misnested_spans", 0)
    tr = SpanTracer(ring_size=16)
    outer = tr.span("outer", "serve")
    inner = tr.span("inner", "serve")
    outer.__enter__()
    inner.__enter__()
    outer.__exit__(None, None, None)  # parent closed before child: misnested
    assert reg.snapshot()["obs.misnested_spans"] == base + 1
    # the child is still tracked (it was not the misnested one)...
    assert [s["name"] for s in tr.open_spans()] == ["inner"]
    inner.__exit__(None, None, None)
    # ...and a clean close leaves nothing behind: no phantom open spans
    assert tr.open_spans() == []
    assert [e["name"] for e in _x_events(tr)] == ["outer", "inner"]
    assert reg.snapshot()["obs.misnested_spans"] == base + 1  # clean pop uncounted


def test_tracer_async_flow_events_and_thread_names():
    """Async (b/e) + flow (s/t/f) events carry the correlation id; registered
    worker threads get Perfetto thread_name metadata rows."""
    import threading

    tr = SpanTracer(ring_size=64)
    tr.async_begin("serve/request", 42, cls="interactive")
    tr.flow_start("serve/req", 42)

    def worker():
        tr.register_thread("serve-worker-x")
        tr.flow_step("serve/req", 42)
        tr.flow_end("serve/req", 42, outcome="completed")
        tr.async_end("serve/request", 42)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    doc = tr.to_chrome_trace()
    evts = doc["traceEvents"]
    corr = [e for e in evts if e.get("id") == 42]
    assert [e["ph"] for e in corr] == ["b", "s", "t", "f", "e"]
    assert len({e["tid"] for e in corr}) == 2  # two threads, one id
    flow_end = next(e for e in corr if e["ph"] == "f")
    assert flow_end["bp"] == "e" and flow_end["args"]["outcome"] == "completed"
    names = {e["args"]["name"] for e in evts if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "serve-worker-x" in names
    # disabled tracer: marks are no-ops
    off = SpanTracer(ring_size=4, enabled=False)
    off.async_begin("x", 1)
    off.register_thread("nope")
    assert [e for e in off.to_chrome_trace()["traceEvents"] if e.get("id")] == []


def test_tracer_module_singleton_configure():
    prev = obs_trace.get_tracer()
    try:
        tr = obs_trace.configure(enabled=True, ring_size=4)
        assert obs_trace.get_tracer() is tr
        with obs_trace.get_tracer().span("x", "data"):
            pass
        assert [e["name"] for e in _x_events(tr)] == ["x"]
    finally:
        obs_trace._TRACER = prev


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_injected_stall(tmp_path):
    """à la test_fault_injection: the loop stops beating mid-span, the
    watchdog must dump a hang report with open spans + registry snapshot."""
    tr = SpanTracer(ring_size=8)
    reg = MetricsRegistry()
    reg.counter("train.rebuilds").inc(3)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.25, poll_s=0.05, tracer=tr, registry=reg)
    wd.start()
    span = tr.span("dispatch/train_step", "dispatch")
    span.__enter__()  # a dispatch that never returns
    wd.arm(step=7)
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    span.__exit__(None, None, None)
    assert report_path.exists(), "watchdog never fired on a stalled loop"
    assert wd.fired
    rep = json.loads(report_path.read_text())
    assert rep["last_step"] == 7
    assert rep["last_phase"] == "step"
    assert rep["seconds_since_last_beat"] >= 0.25
    assert any(s["name"] == "dispatch/train_step" for s in rep["open_spans"])
    assert rep["registry"]["train.rebuilds"] == 3.0
    assert rep["threads"], "thread stacks missing from hang report"
    assert any("MainThread" in name for name in rep["threads"])


def test_watchdog_silent_on_healthy_loop(tmp_path):
    wd = StallWatchdog(str(tmp_path), deadline_s=0.5, poll_s=0.05)
    wd.start()
    for step in range(12):  # ~0.6 s of healthy 50ms steps
        wd.arm(step)
        time.sleep(0.05)
    wd.stop()
    assert not (tmp_path / "hang_report.json").exists()
    assert not wd.fired


def test_watchdog_rejects_nonpositive_deadline(tmp_path):
    with pytest.raises(ValueError, match="deadline"):
        StallWatchdog(str(tmp_path), deadline_s=0.0)


def test_watchdog_info_providers_reach_hang_report(tmp_path):
    """The serving extension: registered info providers (batcher threads,
    in-flight window, breaker state — cli/serve.py wires the real ones)
    land in hang_report.json, and a provider that raises contributes its
    error string instead of killing the report."""
    wd = StallWatchdog(
        str(tmp_path), deadline_s=0.2, poll_s=0.05,
        info_providers={"serving": lambda: {
            "batcher_threads": [{"name": "serve-collect", "alive": True}],
            "inflight": 2,
            "admission": {"breaker": "open"},
        }},
    )

    def broken():
        raise RuntimeError("provider died")

    wd.register_info("broken", broken)
    wd.start()
    wd.arm(step=1, phase="serve")
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    assert report_path.exists()
    rep = json.loads(report_path.read_text())
    assert rep["last_phase"] == "serve"
    serving = rep["info"]["serving"]
    assert serving["inflight"] == 2
    assert serving["batcher_threads"][0]["name"] == "serve-collect"
    assert serving["admission"]["breaker"] == "open"
    assert "provider failed" in rep["info"]["broken"] and "provider died" in rep["info"]["broken"]


def test_watchdog_serving_report_from_live_batcher(tmp_path):
    """End-to-end serving hang report: a pipelined batcher wedged on a hung
    engine, the watchdog's serving section carries the real thread names,
    window occupancy, and breaker state."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.cli.serve import _serving_info
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    class _Echo:
        def predict_async(self, images):
            class _H:
                def result(_s):
                    return images[:, 0, 0, :1]
            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    eng = FaultyEngine(_Echo(), hang_at=0)
    b = PipelinedBatcher(eng, max_batch=1, max_wait_ms=0.0, drain_timeout_s=1.0).start()
    ac = AdmissionController(b)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.2, poll_s=0.05)
    wd.register_info("serving", lambda: _serving_info(b, ac))
    wd.start()
    wd.arm(phase="serve")
    try:
        fut = ac.submit(np.zeros((4, 4, 3), np.float32))
        report_path = tmp_path / "hang_report.json"
        deadline = time.time() + 10
        while time.time() < deadline and not report_path.exists():
            time.sleep(0.05)
        assert report_path.exists()
        rep = json.loads(report_path.read_text())
        serving = rep["info"]["serving"]
        names = {t["name"] for t in serving["batcher_threads"]}
        assert names == {"serve-collect", "serve-complete"}
        assert serving["inflight"] >= 1  # the wedged batch occupies the window
        assert serving["admission"]["breaker"] == "closed"
        assert serving["admission"]["classes"]["interactive"]["in_queue"] >= 1
        # the report NAMES the wedged request: id, class, age, phase
        oldest = serving["oldest_request"]
        assert oldest is not None
        assert oldest["class"] == "interactive"
        assert oldest["age_s"] >= 0.0 and oldest["id"] >= 1
        assert oldest["phase"] in ("queued", "dispatched")
        # the wedged request is also visible in the dumped thread stacks
        assert any("serve-complete" in name for name in rep["threads"])
    finally:
        wd.stop()
        b.stop()  # drain-bounded: the hung engine cannot wedge teardown
        with pytest.raises(Exception):
            fut.result(timeout=1)


# ---------------------------------------------------------------------------
# Logger integration
# ---------------------------------------------------------------------------


def test_logger_degrades_without_tensorflow(tmp_path, monkeypatch, capsys):
    """The satellite fix: tensorboard=True on a TF-less box must warn once
    and keep jsonl logging, not crash the run."""
    monkeypatch.setitem(__import__("sys").modules, "tensorflow", None)
    monkeypatch.setattr(logging_lib, "_TB_WARNED", False)
    log = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=True)
    try:
        assert log._tb is None
        out = capsys.readouterr().out
        assert "tensorboard logging disabled" in out
        # warn once only
        log2 = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=True)
        log2.close()
        assert "tensorboard logging disabled" not in capsys.readouterr().out
        log.scalars(3, {"loss": 1.5}, "train/")
    finally:
        log.close()
    rows = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert rows == [{"step": 3, "train/loss": 1.5}]


def test_logger_scalars_carry_registry_snapshot(tmp_path):
    reg = MetricsRegistry()
    reg.counter("data.decode_failures").inc(2)
    log = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=False)
    try:
        log.set_registry(reg)
        log.scalars(1, {"loss": 0.5}, "train/")
    finally:
        log.close()
    row = json.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
    assert row["train/loss"] == 0.5
    assert row["obs/data.decode_failures"] == 2.0


def test_emit_routes_through_active_logger(capsys):
    log = logging_lib.Logger(None, enabled=True)
    logging_lib.emit("hello from the pipeline")
    out = capsys.readouterr().out
    assert "] hello from the pipeline" in out  # Logger's [HH:MM:SS] prefix
    log.close()
    logging_lib.emit("after close")
    assert capsys.readouterr().out == "after close\n"  # bare fallback


# ---------------------------------------------------------------------------
# fake-data CPU train smoke: trace + snapshot artifacts
# ---------------------------------------------------------------------------


def _smoke_cfg(tmp_path, k_dispatch):
    return config_from_dict({
        "name": "obs-smoke",
        "model": {
            "arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
            "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}],
        },
        "data": {"dataset": "fake", "image_size": 24, "fake_train_size": 64, "fake_eval_size": 16},
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.01, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {
            "batch_size": 32, "eval_batch_size": 16, "epochs": 1, "log_every": 1,
            "compute_dtype": "float32", "log_dir": str(tmp_path),
            "steps_per_dispatch": k_dispatch,
        },
        # trace on; generous watchdog deadline proves it stays silent on a
        # healthy loop even with compiles in the gap
        "obs": {"trace": True, "watchdog_deadline_s": 300.0},
        "dist": {"num_devices": 8},
    })


@pytest.mark.parametrize("k_dispatch", [1, 2])
def test_train_smoke_emits_trace_and_registry_snapshot(tmp_path, k_dispatch):
    result = cli_train.run(_smoke_cfg(tmp_path, k_dispatch))
    assert result["epoch"] == pytest.approx(1.0)

    # valid Chrome-trace JSON with spans from all five core categories
    doc = json.loads((tmp_path / "obs_trace.json").read_text())
    evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evts, "no spans recorded"
    for e in evts:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    cats = {e["cat"] for e in evts}
    assert {"data", "dispatch", "sync", "eval", "ckpt"} <= cats, cats
    names = {e["name"] for e in evts}
    if k_dispatch > 1:
        # spans COMPOSE with grouped dispatch instead of forcing it off
        assert "dispatch/grouped_step" in names
        grouped = next(e for e in evts if e["name"] == "dispatch/grouped_step")
        assert grouped["args"]["steps"] == k_dispatch
    else:
        assert "dispatch/train_step" in names

    # registry snapshot written at run end
    snap = json.loads((tmp_path / "obs_registry.json").read_text())
    assert snap.get("ckpt.saves", 0) >= 1
    assert snap.get("eval.passes", 0) >= 1
    assert "ckpt.wait_seconds.count" in snap

    # every scalars row carries the obs/ snapshot
    rows = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert rows and all(any(k.startswith("obs/") for k in r) for r in rows)

    # healthy loop: armed watchdog stayed silent
    assert not (tmp_path / "hang_report.json").exists()


# ---------------------------------------------------------------------------
# scripts/obs_report.py
# ---------------------------------------------------------------------------


def _obs_report_mod():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_summary(tmp_path, capsys):
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "train/loss": 2.0, "train/images_per_sec": 100.0,
                    "obs/ckpt.saves": 0.0}) + "\n"
        + json.dumps({"step": 2, "eval/top1": 0.75, "eval/loss": 1.1}) + "\n"
    )
    (tmp_path / "obs_registry.json").write_text(
        json.dumps({"ckpt.saves": 1.0, "train.rebuilds": 2.0}))
    (tmp_path / "hang_report.json").write_text(json.dumps({
        "seconds_since_last_beat": 12.5, "deadline_s": 5.0, "last_step": 42,
        "last_phase": "step",
        "open_spans": [{"name": "dispatch/train_step", "cat": "dispatch", "open_for_s": 12.0}],
        "registry": {}, "threads": {"MainThread-1": ["..."]},
    }))
    rc = _obs_report_mod().main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "last train/loss = 2" in out
    assert "best eval/top1 = 0.75" in out
    assert "ckpt.saves = 1" in out
    assert "HANG REPORT" in out
    assert "dispatch/train_step" in out


def test_obs_report_device_section(tmp_path, capsys):
    """The device-telemetry section: compile events, per-executable cost,
    dispatch efficiency, memory gauges (obs/device.py surfaces)."""
    (tmp_path / "obs_registry.json").write_text(json.dumps({
        "obs.compiles": 3.0, "obs.compile_seconds.p50": 1.5,
        "obs.compile_seconds.max": 2.0, "obs.compile_seconds.sum": 4.0,
        "obs.cost_flops.serve_b8_s224_k1": 1.2e9,
        "obs.cost_bytes.serve_b8_s224_k1": 3.4e8,
        "serve.achieved_flops_per_s": 2.5e9, "serve.run_seconds.count": 4.0,
        "host.rss_bytes": 5e8, "device.live_buffer_bytes": 1e7,
        "device.bytes_in_use.d0": 2e9, "device.peak_bytes_in_use.d0": 3e9,
    }))
    rc = _obs_report_mod().main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "## device (compile / cost / memory)" in out
    assert "compiles = 3" in out and "p50 1.50s" in out
    assert "[serve_b8_s224_k1] 1.200 GFLOP, 340.0 MB accessed" in out
    assert "dispatch efficiency: 2.50 achieved GFLOP/s" in out
    assert "host rss 500 MB" in out
    assert "d0 in-use 2000 MB (peak 3000)" in out


def test_obs_report_missing_dir(capsys):
    assert _obs_report_mod().main(["/definitely/not/a/dir"]) == 2


def test_obs_report_requests_waterfalls_and_quantiles(tmp_path, capsys):
    """--requests renders per-request waterfalls from the trace's async
    events and a per-phase quantile table from the registry snapshot."""
    us = 1000.0  # µs timestamps in the trace
    events = [
        # request 17: queued 2 ms, in-flight 3 ms, across two threads
        {"name": "serve/request", "ph": "b", "id": 17, "tid": 1, "ts": 0,
         "args": {"cls": "interactive", "deadline_ms": 50.0}},
        {"name": "serve/queued", "ph": "b", "id": 17, "tid": 1, "ts": 0},
        {"name": "serve/queued", "ph": "e", "id": 17, "tid": 2, "ts": 2 * us},
        {"name": "serve/inflight", "ph": "b", "id": 17, "tid": 2, "ts": 2 * us},
        {"name": "serve/inflight", "ph": "e", "id": 17, "tid": 3, "ts": 5 * us},
        {"name": "serve/request", "ph": "e", "id": 17, "tid": 3, "ts": 5.2 * us,
         "args": {"outcome": "completed"}},
        # a flow step rides along and must not confuse the waterfall parse
        {"name": "serve/req", "ph": "t", "id": 17, "tid": 2, "ts": 2 * us},
    ]
    (tmp_path / "obs_trace.json").write_text(json.dumps({"traceEvents": events}))
    (tmp_path / "obs_registry.json").write_text(json.dumps({
        "serve.queue_wait_seconds.count": 4.0,
        "serve.queue_wait_seconds.p50": 0.002, "serve.queue_wait_seconds.p95": 0.003,
        "serve.queue_wait_seconds.p99": 0.0031, "serve.queue_wait_seconds.min": 0.001,
        "serve.queue_wait_seconds.max": 0.0032,
        "serve.latency_seconds.interactive.count": 4.0,
        "serve.latency_seconds.interactive.p50": 0.005,
        "serve.latency_seconds.interactive.p99": 0.009,
    }))
    rc = _obs_report_mod().main([str(tmp_path), "--requests"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "#17" in out and "class=interactive" in out
    assert "total=5.20ms" in out and "queued=2.00ms" in out and "inflight=3.00ms" in out
    assert "[completed]" in out
    assert "queue wait" in out and "latency [interactive]" in out
    assert "p50_ms" in out and "p99_ms" in out


# ---------------------------------------------------------------------------
# fleet federation + flight recorder (obs/fleet.py) and trace merge
# (scripts/trace_merge.py) — ISSUE 17
# ---------------------------------------------------------------------------


class _StubVarz:
    """A backend whose /varz is a callable — the scrape loop's only client
    surface (ReplicaClient.varz -> (status, doc))."""

    def __init__(self, doc_fn, status=200):
        self._doc_fn = doc_fn
        self._status = status
        self.calls = 0

    def varz(self, timeout_s=2.0):
        self.calls += 1
        if isinstance(self._status, Exception):
            raise self._status
        return self._status, self._doc_fn()


def _replica_varz(reg, rid, build=None):
    """A /varz document shaped like serve/frontend.py's, from a registry."""
    return {
        "replica": {"replica_id": rid},
        "build_info": build or {},
        "metrics": reg.snapshot(),
        "histograms": reg.histograms_state(),
        "admission": {"queued_total": 0},
        "draining": False,
    }


def test_registry_histograms_state_is_raw_and_mergeable():
    """Histogram.state() ships RAW per-bucket counts (not cumulative) plus
    bounds/count/sum/min/max — the exact payload quantiles_from_counts
    consumes, so a scraper recomputes quantiles losslessly."""
    from yet_another_mobilenet_series_tpu.obs.registry import quantiles_from_counts

    reg = MetricsRegistry()
    h = reg.histogram("serve.latency_seconds.interactive", bounds=[0.01, 0.1])
    h.observe(0.005)
    h.observe(0.5)
    reg.counter("serve.completed.interactive").inc()  # not a histogram: excluded
    state = reg.histograms_state()
    assert set(state) == {"serve.latency_seconds.interactive"}
    st = state["serve.latency_seconds.interactive"]
    assert st["bounds"] == [0.01, 0.1]
    assert st["counts"] == [1, 0, 1]  # raw slots incl. overflow, NOT cumulative
    assert st["count"] == 2 and st["sum"] == 0.505
    assert st["min"] == 0.005 and st["max"] == 0.5
    (p50,) = quantiles_from_counts(st["bounds"], st["counts"], (0.5,),
                                   vmin=st["min"], vmax=st["max"])
    assert 0.005 <= p50 <= 0.5
    assert json.loads(json.dumps(st)) == st  # JSON-safe for /varz


def test_fleet_federation_p99_matches_pooled_reference():
    """The federation-correctness property (ISSUE 17 acceptance): the fleet
    windowed p99 computed from SUMMED per-replica bucket-count deltas must
    equal the quantile of one histogram fed every pooled observation —
    identical ladders make the merge exact, not an average of averages.
    Includes the edges: a replica with NO histograms at all, and an
    all-zero window (no traffic between scrapes) reading 0."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.obs.fleet import FleetFederation
    from yet_another_mobilenet_series_tpu.obs.registry import quantiles_from_counts

    regs = [MetricsRegistry() for _ in range(3)]
    backends = [(f"127.0.0.1:900{i}",
                 _StubVarz(lambda i=i: _replica_varz(regs[i], f"r{i}")))
                for i in range(3)]
    fed = FleetFederation(lambda: backends)
    rng = np.random.RandomState(11)
    lat = "serve.latency_seconds.interactive"

    # pre-window history the baseline scrape must consume, NOT leak into
    # the first window
    for reg in regs[:2]:
        for v in np.exp(rng.uniform(np.log(1e-3), np.log(2.0), 50)):
            reg.histogram(lat).observe(float(v))
    fed.scrape_once()

    # the window: replicas 0 and 1 observe, replica 2 stays histogram-free
    window = []
    for reg in regs[:2]:
        vs = np.exp(rng.uniform(np.log(1e-3), np.log(2.0), 400))
        for v in vs:
            reg.histogram(lat).observe(float(v))
        window.extend(float(v) for v in vs)
    summary = fed.scrape_once()
    assert summary == {"scraped": 3, "errors": 0}
    assert get_registry().gauge("fleet.federated_replicas").value == 3

    ref = MetricsRegistry().histogram("ref")  # same default ladder
    for v in window:
        ref.observe(v)
    (ref_p99,) = quantiles_from_counts(
        list(ref.bounds), list(ref.bucket_counts()), (0.99,))
    fed_p99 = get_registry().gauge("fleet.window_p99_seconds.interactive").value
    assert fed_p99 == ref_p99  # exact, same interpolation over equal counts
    assert fed.snapshot()["window_p99_s"]["interactive"] == ref_p99

    # merged CUMULATIVE counts = element-wise sum of both lifetimes so far
    merged = fed.merged_counts()[lat]
    per_rep = [list(r.histogram(lat).bucket_counts()) for r in regs[:2]]
    assert merged["counts"] == [a + b for a, b in zip(*per_rep)]

    # all-zero window: no traffic between scrapes reads a 0 gauge, not NaN
    fed.scrape_once()
    assert get_registry().gauge("fleet.window_p99_seconds.interactive").value == 0.0


def test_fleet_federation_replica_restart_not_double_counted():
    """Counter-reset handling: a replica restart zeroes its histograms; the
    merged cumulative counts must carry BOTH lifetimes exactly once (a
    naive cumulative sum would lose the first or double the second)."""
    from yet_another_mobilenet_series_tpu.obs.fleet import FleetFederation

    lat = "serve.latency_seconds.interactive"
    holder = {"reg": MetricsRegistry()}
    backends = [("127.0.0.1:9000",
                 _StubVarz(lambda: _replica_varz(holder["reg"], "r0")))]
    fed = FleetFederation(lambda: backends)
    for _ in range(10):
        holder["reg"].histogram(lat).observe(0.01)
    fed.scrape_once()
    holder["reg"] = MetricsRegistry()  # kill -9 + respawn: fresh process
    for _ in range(4):
        holder["reg"].histogram(lat).observe(0.01)
    fed.scrape_once()
    assert sum(fed.merged_counts()[lat]["counts"]) == 14

    # a dead backend is a skipped scrape, never an exception out of the loop
    backends.append(("127.0.0.1:9001", _StubVarz(None, status=OSError("down"))))
    summary = fed.scrape_once()
    assert summary == {"scraped": 1, "errors": 1}


def test_fleet_federation_slo_feed_and_fast_burn_incident(tmp_path):
    """The scrape loop feeds summed completed/bad deltas into the SLO
    tracker; sustained burn over BOTH windows trips fast_burn, which arms
    the flight recorder and the dump names the reason."""
    from yet_another_mobilenet_series_tpu.obs.fleet import FleetFederation, FlightRecorder
    from yet_another_mobilenet_series_tpu.serve.signals import SLOTracker

    t = [0.0]
    slo = SLOTracker(error_budget=0.1, short_window_s=5.0, long_window_s=50.0,
                     fast_burn=2.0, clock=lambda: t[0])
    reg = MetricsRegistry()
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0)
    fed = FleetFederation(
        lambda: [("a", _StubVarz(lambda: _replica_varz(reg, "ra")))],
        slo=slo, recorder=rec)
    fed.scrape_once()  # baseline
    for _ in range(60):  # 50% bad at a 10% budget = 5x burn, both windows
        t[0] += 1.0
        reg.counter("serve.completed.interactive").inc(5)
        reg.counter("serve.rejected.interactive").inc(5)
        fed.scrape_once()
    assert slo.fast_burn
    assert get_registry().gauge("fleet.slo_burn_rate.short").value >= 2.0
    path = rec.maybe_dump(fed)
    assert path and os.path.basename(path) == "incident_slo_fast_burn.json"
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "slo_fast_burn"
    assert "fleet" in doc and "replica_varz" in doc
    assert doc["fleet"]["slo"]["fast_burn"] is True
    assert any(e["kind"] == "trigger" for e in doc["events"])


def test_fleet_render_prometheus_golden():
    """Replica-labeled exposition golden: every replica's histograms under
    the fleet_ namespace (cumulative buckets, le labels, per-family TYPE
    once), build_info from every replica under ONE family, deterministic
    ordering — the exact text the router frontend appends to /metrics."""
    from yet_another_mobilenet_series_tpu.obs.fleet import FleetFederation

    r0, r1 = MetricsRegistry(), MetricsRegistry()
    r0.histogram("serve.latency_seconds.interactive", bounds=[0.01, 0.1]).observe(0.005)
    r0.histogram("serve.queue_wait_seconds", bounds=[0.01]).observe(0.005)
    r1.histogram("serve.latency_seconds.interactive", bounds=[0.01, 0.1]).observe(0.5)
    backends = [
        ("127.0.0.1:9000", _StubVarz(lambda: _replica_varz(
            r0, "r0", build={"git_sha": "abc", "platform": "cpu"}))),
        ("127.0.0.1:9001", _StubVarz(lambda: _replica_varz(
            r1, "r1", build={"git_sha": "abc", "platform": "cpu"}))),
    ]
    fed = FleetFederation(lambda: backends)
    assert fed.render_prometheus() == ""  # nothing scraped yet
    fed.scrape_once()
    golden = "\n".join([
        '# TYPE fleet_build_info gauge',
        'fleet_build_info{replica="r0",git_sha="abc",platform="cpu"} 1',
        'fleet_build_info{replica="r1",git_sha="abc",platform="cpu"} 1',
        '# TYPE fleet_serve_latency_seconds histogram',
        'fleet_serve_latency_seconds_bucket{replica="r0",class="interactive",le="0.01"} 1',
        'fleet_serve_latency_seconds_bucket{replica="r0",class="interactive",le="0.1"} 1',
        'fleet_serve_latency_seconds_bucket{replica="r0",class="interactive",le="+Inf"} 1',
        'fleet_serve_latency_seconds_sum{replica="r0",class="interactive"} 0.005',
        'fleet_serve_latency_seconds_count{replica="r0",class="interactive"} 1',
        '# TYPE fleet_serve_queue_wait_seconds histogram',
        'fleet_serve_queue_wait_seconds_bucket{replica="r0",le="0.01"} 1',
        'fleet_serve_queue_wait_seconds_bucket{replica="r0",le="+Inf"} 1',
        'fleet_serve_queue_wait_seconds_sum{replica="r0"} 0.005',
        'fleet_serve_queue_wait_seconds_count{replica="r0"} 1',
        'fleet_serve_latency_seconds_bucket{replica="r1",class="interactive",le="0.01"} 0',
        'fleet_serve_latency_seconds_bucket{replica="r1",class="interactive",le="0.1"} 0',
        'fleet_serve_latency_seconds_bucket{replica="r1",class="interactive",le="+Inf"} 1',
        'fleet_serve_latency_seconds_sum{replica="r1",class="interactive"} 0.5',
        'fleet_serve_latency_seconds_count{replica="r1",class="interactive"} 1',
    ]) + "\n"
    assert fed.render_prometheus() == golden


def test_slo_tracker_two_window_gating_and_pruning():
    """Multi-window burn-rate semantics: a short error burst saturates the
    SHORT window but the long window's healthy history gates the alarm;
    sustained burn floods both and trips fast_burn. Ticks prune past the
    long window."""
    from yet_another_mobilenet_series_tpu.serve.signals import SLOTracker

    t = [0.0]
    s = SLOTracker(target_p99_ms=100.0, error_budget=0.01, short_window_s=10.0,
                   long_window_s=100.0, fast_burn=14.0, clock=lambda: t[0])
    for _ in range(90):
        t[0] += 1.0
        s.observe(100, 0, p99_s=0.05)
    assert s.burn_rate(10.0) == 0.0 and not s.fast_burn
    for _ in range(4):  # the burst: 50% errors at a 1% budget
        t[0] += 1.0
        s.observe(100, 50, p99_s=0.05)
    assert s.burn_rate(10.0) >= 14.0
    assert s.burn_rate(100.0) < 14.0
    assert not s.fast_burn  # gated by the long window
    for _ in range(100):  # sustained: both windows saturate
        t[0] += 1.0
        s.observe(100, 50, p99_s=0.05)
    assert s.fast_burn
    st = s.state()
    assert st["fast_burn"] and st["burn_short"] >= 14.0 and st["burn_long"] >= 14.0
    assert st["ticks"] <= 101  # pruned to the long window


def test_slo_tracker_latency_breach_burns_budget():
    """A p99 above target burns budget even with zero errors: the latency
    burn is the breached-tick fraction over the window / budget."""
    from yet_another_mobilenet_series_tpu.serve.signals import SLOTracker

    t = [0.0]
    s = SLOTracker(target_p99_ms=100.0, error_budget=0.1, short_window_s=10.0,
                   long_window_s=100.0, clock=lambda: t[0])
    for _ in range(10):
        t[0] += 1.0
        s.observe(100, 0, p99_s=0.5)  # 5x over target, no errors
    assert s.burn_rate(10.0) == 10.0  # every tick breached / 0.1 budget
    with pytest.raises(ValueError):
        SLOTracker(error_budget=0.0)
    with pytest.raises(ValueError):
        SLOTracker(short_window_s=60.0, long_window_s=30.0)


def test_flight_recorder_ring_triggers_and_rate_limit(tmp_path):
    """Ring semantics + arming: only trigger kinds arm a dump, the rate
    limiter keeps an armed trigger pending (never drops it), a dump
    disarms, and the ring is bounded."""
    from yet_another_mobilenet_series_tpu.obs.fleet import FlightRecorder

    rec = FlightRecorder(str(tmp_path), ring=8, min_interval_s=3600.0)
    assert rec.maybe_dump() is None  # nothing armed
    rec.record("hedge_outcome", winner="hedge")  # significant but not a trigger
    assert rec.maybe_dump() is None
    rec.record("ejection", replica="127.0.0.1:9000", consecutive_failures=2)
    p = rec.maybe_dump()
    assert p and os.path.basename(p) == "incident_ejection.json"
    with open(p) as f:
        doc = json.load(f)
    assert [e["kind"] for e in doc["events"]] == ["hedge_outcome", "ejection"]
    assert all("t_unix" in e for e in doc["events"])
    assert "registry" in doc and "fleet" not in doc  # no federation passed
    # rate-limited: the new trigger stays ARMED until the limiter reopens
    rec.record("lease_expired", replica="127.0.0.1:9001")
    assert rec.maybe_dump() is None
    rec.min_interval_s = 0.0
    p2 = rec.maybe_dump()
    assert p2 and os.path.basename(p2) == "incident_lease_expired.json"
    assert rec.maybe_dump() is None  # disarmed by the dump
    for i in range(50):
        rec.record("breaker_flip", state=i % 3)
    assert len(rec.events()) == 8  # bounded ring


def test_flight_recorder_brownout_arming(tmp_path):
    """The recorder is a brownout TARGET: transitions land in the ring, a
    climb to incident_level arms a dump, recovery back down does not."""
    from yet_another_mobilenet_series_tpu.obs.fleet import FlightRecorder

    class _Policy:
        def __init__(self, level):
            self.level = level
            self.shed_classes = {"batch"} if level >= 3 else set()
            self.hedging = level < 1

    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0, incident_level=3)
    rec.apply_brownout(_Policy(1))
    rec.apply_brownout(_Policy(1))  # same level: no duplicate event
    assert rec.maybe_dump() is None  # below incident_level
    rec.apply_brownout(_Policy(3))
    p = rec.maybe_dump()
    assert p and os.path.basename(p) == "incident_brownout_l3.json"
    with open(p) as f:
        doc = json.load(f)
    trans = [e for e in doc["events"] if e["kind"] == "brownout_transition"]
    assert [e["level"] for e in trans] == [1, 3]
    assert trans[-1]["shed_classes"] == ["batch"]
    rec.apply_brownout(_Policy(4))
    assert rec.maybe_dump() is not None
    rec.apply_brownout(_Policy(3))  # recovery DOWN through the level
    assert rec.maybe_dump() is None


def _trace_merge_mod():
    spec = importlib.util.spec_from_file_location(
        "trace_merge", os.path.join(REPO, "scripts", "trace_merge.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_merge_aligns_clocks_and_scopes_ids():
    """The merge invariants: wall-origin offsets shift every non-metadata
    event onto the earliest process's timeline, colliding pids get their
    own lanes, per-process async/flow ids are remapped so equal request
    ids never fuse across processes — EXCEPT fleet/leg flows, whose ids
    are the cross-process arrow and must survive untouched."""
    tm = _trace_merge_mod()
    router = {
        "pid": 100, "process_name": "router", "origin_unix": 1000.0,
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 100, "tid": 0, "ts": 0},
            {"ph": "b", "cat": "serve", "name": "serve/request", "id": 5,
             "pid": 100, "tid": 1, "ts": 10.0},
            {"ph": "s", "cat": "serve", "name": "fleet/leg", "id": 80,
             "pid": 100, "tid": 1, "ts": 12.0, "args": {"trace": 5, "leg": "primary"}},
        ],
    }
    replica = {
        "pid": 100, "process_name": "r0", "origin_unix": 1000.5,  # pid collision
        "traceEvents": [
            {"ph": "b", "cat": "serve", "name": "serve/request", "id": 5,
             "pid": 100, "tid": 1, "ts": 3.0, "args": {"trace": 5}},
            {"ph": "f", "bp": "e", "cat": "serve", "name": "fleet/leg", "id": 80,
             "pid": 100, "tid": 1, "ts": 4.0},
        ],
    }
    merged = tm.merge([router, replica], sources=["router.json", "r0.json"])
    assert "warnings" not in merged
    procs = {p["process_name"]: p for p in merged["processes"]}
    assert procs["router"]["pid"] == 100
    assert procs["r0"]["pid"] != 100  # collision remapped to its own lane
    assert procs["router"]["offset_us"] == 0.0
    assert procs["r0"]["offset_us"] == 500000.0  # +0.5 s wall-origin gap
    ev = {(e["pid"], e["ph"], e["name"]): e for e in merged["traceEvents"]}
    rpid = procs["r0"]["pid"]
    assert ev[(rpid, "b", "serve/request")]["ts"] == 3.0 + 500000.0
    assert ev[(100, "M", "process_name")]["ts"] == 0  # metadata never shifts
    a = ev[(100, "b", "serve/request")]["id"]
    b = ev[(rpid, "b", "serve/request")]["id"]
    assert a != b  # raw id 5 no longer fuses across processes
    assert a % tm.ID_STRIDE == 5 and b % tm.ID_STRIDE == 5
    assert ev[(100, "s", "fleet/leg")]["id"] == 80
    assert ev[(rpid, "f", "fleet/leg")]["id"] == 80  # the arrow survives


def test_trace_merge_cli_discovers_writes_and_warns(tmp_path, capsys):
    """main(): discovers the fleet layout (router + r*/ sorted), writes
    merged_trace.json atomically, prints the process table, and a doc
    missing origin_unix degrades to a warning, never a crash."""
    tm = _trace_merge_mod()
    doc = {"pid": 1, "process_name": "router", "origin_unix": 5.0,
           "traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                            "ts": 1.0, "dur": 2.0}]}
    (tmp_path / "obs_trace.json").write_text(json.dumps(doc))
    for i, origin in enumerate((5.25, None)):
        d = dict(doc, pid=2 + i, process_name=f"r{i}")
        if origin is None:
            d.pop("origin_unix")
        else:
            d["origin_unix"] = origin
        (tmp_path / f"r{i}").mkdir()
        (tmp_path / f"r{i}" / "obs_trace.json").write_text(json.dumps(d))
    assert tm.main([str(tmp_path)]) == 0
    printed = capsys.readouterr()
    out = json.load(open(tmp_path / "merged_trace.json"))
    assert [p["process_name"] for p in out["processes"]] == ["router", "r0", "r1"]
    assert [p["offset_us"] for p in out["processes"]] == [0.0, 250000.0, 0.0]
    assert len(out["warnings"]) == 1 and "r1" in out["warnings"][0]
    assert "merged_trace.json" in printed.out
    # a dir with no traces is a clean usage error
    (tmp_path / "empty").mkdir()
    assert tm.main([str(tmp_path / "empty")]) == 2


def test_obs_report_fleet_section(tmp_path, capsys):
    """--fleet renders replica layout, the merged-trace pointer, and the
    incident artifact census (reason, event kinds, SLO state)."""
    (tmp_path / "r0").mkdir()
    (tmp_path / "r0" / "obs_trace.json").write_text(json.dumps({"traceEvents": []}))
    (tmp_path / "incident_ejection.json").write_text(json.dumps({
        "reason": "ejection", "t_unix": 1000.0, "brownout_level": 0,
        "events": [{"t_unix": 999.0, "kind": "ejection", "replica": "127.0.0.1:9001"}],
        "registry": {},
        "fleet": {"replicas": {"127.0.0.1:9000": {}}, "window_p99_s": {"interactive": 0.012},
                  "scrapes": 5, "scrape_errors": 0,
                  "slo": {"burn_short": 1.5, "burn_long": 0.2, "fast_burn": False,
                          "target_p99_ms": 250.0, "error_budget": 0.01}},
    }))
    rc = _obs_report_mod().main([str(tmp_path), "--fleet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "## fleet" in out
    assert "replica slots: 1 (1 with traces)" in out
    assert "trace_merge.py" in out  # merged trace not built yet: the hint
    assert "incident_ejection.json" in out and "reason = ejection" in out
    assert "ejection x1" in out
    assert "window p99 [interactive] = 12.00 ms" in out
    assert "burn short 1.50" in out
