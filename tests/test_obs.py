"""obs/ subsystem tests: registry semantics, span tracer (nesting, ring
eviction, Chrome-trace schema), stall watchdog (fires on an injected stall,
silent on a healthy loop), Logger integration (TF-less degrade, registry
snapshots in scalars rows), the fake-data train smoke (trace + snapshot
artifacts for steps_per_dispatch 1 and >1), and scripts/obs_report.py."""

import importlib.util
import json
import os
import time

import pytest

from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.obs.registry import MetricsRegistry, get_registry
from yet_another_mobilenet_series_tpu.obs.trace import SpanTracer
from yet_another_mobilenet_series_tpu.obs import trace as obs_trace
from yet_another_mobilenet_series_tpu.obs.watchdog import StallWatchdog
from yet_another_mobilenet_series_tpu.utils import logging as logging_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("a.hits").inc()
    reg.counter("a.hits").inc(2)
    reg.gauge("a.level").set(7.5)
    h = reg.histogram("a.wait")
    h.observe(1.0)
    h.observe(3.0)
    snap = reg.snapshot()
    assert snap["a.hits"] == 3.0
    assert snap["a.level"] == 7.5
    assert snap["a.wait.count"] == 2.0
    assert snap["a.wait.sum"] == 4.0
    assert snap["a.wait.mean"] == 2.0
    assert snap["a.wait.max"] == 3.0
    # get-or-create returns the SAME metric object
    assert reg.counter("a.hits") is reg.counter("a.hits")


def test_registry_type_conflict_and_negative_inc():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")
    with pytest.raises(ValueError, match="cannot decrease"):
        reg.counter("x").inc(-1)


def test_registry_gauge_callback_and_fault_isolation():
    reg = MetricsRegistry()
    src = {"v": 5}
    g = reg.gauge("pull")
    g.set_fn(lambda: src["v"])
    assert reg.snapshot()["pull"] == 5.0
    src["v"] = 9
    assert reg.snapshot()["pull"] == 9.0
    # a dying producer keeps the last good reading, never raises
    g.set_fn(lambda: 1 / 0)
    assert reg.snapshot()["pull"] == 9.0


def test_registry_reset():
    reg = MetricsRegistry()
    reg.counter("x").inc()
    reg.reset()
    assert reg.snapshot() == {}


# ---------------------------------------------------------------------------
# bucketed histograms: quantiles, min, concurrency, Prometheus exposition
# ---------------------------------------------------------------------------


def test_histogram_min_reported():
    """The satellite fix: vmin was tracked under the lock but never
    reported — it must reach summary(), snapshot(), and stay correct."""
    reg = MetricsRegistry()
    h = reg.histogram("t.wait")
    for v in (0.2, 0.005, 0.07):
        h.observe(v)
    s = h.summary()
    assert s["min"] == 0.005 and s["max"] == 0.2
    snap = reg.snapshot()
    assert snap["t.wait.min"] == 0.005
    # empty histogram reports zeros, never inf
    assert reg.histogram("t.empty").summary()["min"] == 0.0


def test_histogram_bucketed_quantiles_vs_sorted_reference():
    """Bucketed p50/p95/p99 must land within one bucket width of the exact
    sorted-sample quantile (the estimator interpolates inside the bucket
    that crosses the target rank)."""
    import numpy as np

    rng = np.random.RandomState(7)
    samples = np.exp(rng.uniform(np.log(2e-4), np.log(20.0), 4000))
    h = MetricsRegistry().histogram("t.lat")
    for v in samples:
        h.observe(float(v))
    for q in (0.5, 0.95, 0.99):
        ref = float(np.quantile(samples, q))
        est = h.quantile(q)
        # one bucket on the default quarter-decade ladder is a 10**0.25
        # (~1.78x) span: the estimate must stay inside the ref's bucket
        assert ref / (10 ** 0.25) <= est <= ref * (10 ** 0.25), (q, ref, est)
    # quantiles are monotone and clamped to the observed range
    s = h.summary()
    assert s["min"] <= s["p50"] <= s["p95"] <= s["p99"] <= s["max"]


def test_histogram_concurrent_observe_consistent():
    import threading

    h = MetricsRegistry().histogram("t.conc")
    n_threads, per_thread = 8, 500

    def worker(i):
        for j in range(per_thread):
            h.observe(1e-3 * (1 + (i * per_thread + j) % 97))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * per_thread
    assert sum(h.bucket_counts()) == h.count  # no lost bucket increments
    assert h.summary()["p50"] > 0


def test_histogram_custom_default_buckets():
    """set_default_buckets (the obs.histogram_buckets config knob) applies
    to histograms created AFTER the call; existing ladders are untouched."""
    reg = MetricsRegistry()
    before = reg.histogram("a")
    reg.set_default_buckets([0.1, 1.0, 10.0])
    after = reg.histogram("b")
    assert after.bounds == (0.1, 1.0, 10.0)
    assert before.bounds != after.bounds
    assert reg.histogram("a") is before  # get-or-create keeps the old ladder


def test_quantiles_from_counts_empty_and_zero_observations():
    """Edge cases the serving bench's delta math can hit: an all-zero count
    window (no observations between snapshots) and an empty-histogram
    summary must yield zeros, never a divide-by-zero or an inf clamp."""
    from yet_another_mobilenet_series_tpu.obs.registry import (
        DEFAULT_BUCKET_BOUNDS, quantiles_from_counts)

    counts = [0] * (len(DEFAULT_BUCKET_BOUNDS) + 1)
    assert quantiles_from_counts(DEFAULT_BUCKET_BOUNDS, counts, (0.5, 0.95, 0.99)) == [0.0, 0.0, 0.0]
    # vmin/vmax still at their empty sentinels (inf/-inf) must not leak out
    assert quantiles_from_counts(
        DEFAULT_BUCKET_BOUNDS, counts, (0.5,), vmin=float("inf"), vmax=float("-inf")) == [0.0]
    h = MetricsRegistry().histogram("t.never_observed")
    s = h.summary()
    assert s == {"count": 0.0, "sum": 0.0, "mean": 0.0, "min": 0.0, "max": 0.0,
                 "p50": 0.0, "p95": 0.0, "p99": 0.0}
    assert h.quantile(0.99) == 0.0


def test_render_prometheus_empty_histogram():
    """A histogram with no samples still renders a complete, finite family:
    zero cumulative buckets, zero sum/count, zero quantiles — a scraper must
    never see NaN/inf from a warmed-but-idle latency metric."""
    reg = MetricsRegistry()
    reg.histogram("serve.latency_seconds.batch", bounds=[0.01, 0.1])
    golden = "\n".join([
        '# TYPE serve_latency_seconds histogram',
        'serve_latency_seconds_bucket{class="batch",le="0.01"} 0',
        'serve_latency_seconds_bucket{class="batch",le="0.1"} 0',
        'serve_latency_seconds_bucket{class="batch",le="+Inf"} 0',
        'serve_latency_seconds_sum{class="batch"} 0',
        'serve_latency_seconds_count{class="batch"} 0',
        'serve_latency_seconds{class="batch",quantile="0.5"} 0',
        'serve_latency_seconds{class="batch",quantile="0.95"} 0',
        'serve_latency_seconds{class="batch",quantile="0.99"} 0',
    ]) + "\n"
    assert reg.render_prometheus() == golden
    for v in reg.snapshot().values():
        assert v == v and abs(v) != float("inf")  # finite, not NaN


def test_render_prometheus_golden():
    """Exposition golden: counter/gauge samples, a labeled per-class
    histogram with cumulative buckets + quantile lines, TYPE lines once per
    family — the exact text GET /metrics serves."""
    reg = MetricsRegistry()
    reg.counter("serve.requests").inc(5)
    reg.counter("serve.requests.interactive").inc(3)
    reg.gauge("serve.inflight").set(2)
    h = reg.histogram("serve.latency_seconds.interactive", bounds=[0.01, 0.1, 1.0])
    for v in (0.005, 0.05, 0.5):
        h.observe(v)
    golden = "\n".join([
        '# TYPE serve_inflight gauge',
        'serve_inflight 2',
        '# TYPE serve_latency_seconds histogram',
        'serve_latency_seconds_bucket{class="interactive",le="0.01"} 1',
        'serve_latency_seconds_bucket{class="interactive",le="0.1"} 2',
        'serve_latency_seconds_bucket{class="interactive",le="1"} 3',
        'serve_latency_seconds_bucket{class="interactive",le="+Inf"} 3',
        'serve_latency_seconds_sum{class="interactive"} 0.555',
        'serve_latency_seconds_count{class="interactive"} 3',
        'serve_latency_seconds{class="interactive",quantile="0.5"} 0.055',
        'serve_latency_seconds{class="interactive",quantile="0.95"} 0.44',
        'serve_latency_seconds{class="interactive",quantile="0.99"} 0.488',
        '# TYPE serve_requests counter',
        'serve_requests 5',
        'serve_requests{class="interactive"} 3',
    ]) + "\n"
    assert reg.render_prometheus() == golden


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def _x_events(tracer):
    return [e for e in tracer.to_chrome_trace()["traceEvents"] if e["ph"] == "X"]


def test_tracer_span_nesting_and_containment():
    tr = SpanTracer(ring_size=16)
    with tr.span("outer", "dispatch", steps=2):
        with tr.span("inner", "sync"):
            time.sleep(0.001)
    evts = _x_events(tr)
    # completion order: inner closes first
    assert [e["name"] for e in evts] == ["inner", "outer"]
    inner, outer = evts
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["args"] == {"steps": 2}


def test_tracer_ring_eviction():
    tr = SpanTracer(ring_size=4)
    for i in range(10):
        with tr.span(f"s{i}", "data"):
            pass
    evts = _x_events(tr)
    assert [e["name"] for e in evts] == ["s6", "s7", "s8", "s9"]


def test_tracer_chrome_trace_schema(tmp_path):
    tr = SpanTracer(ring_size=8)
    with tr.span("a", "data"):
        pass
    path = tr.write(str(tmp_path / "trace.json"))
    doc = json.loads(open(path).read())
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M")
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["cat"], str)


def test_tracer_disabled_is_noop():
    tr = SpanTracer(ring_size=8, enabled=False)
    s1 = tr.span("a", "data")
    s2 = tr.span("b", "sync")
    assert s1 is s2  # the shared null span: zero allocation on the hot path
    with s1:
        pass
    assert _x_events(tr) == []


def test_tracer_open_spans_readout():
    tr = SpanTracer(ring_size=8)
    with tr.span("outer", "dispatch"):
        with tr.span("inner", "data"):
            open_now = tr.open_spans()
            assert [s["name"] for s in open_now] == ["outer", "inner"]
            assert all(s["open_for_s"] >= 0 for s in open_now)
    assert tr.open_spans() == []


def test_tracer_misnested_exit_recovered_and_counted():
    """The satellite fix: an out-of-order exit must remove the span by
    identity (not leave it stuck in _open polluting every later hang
    report) and count obs.misnested_spans."""
    reg = get_registry()
    base = reg.snapshot().get("obs.misnested_spans", 0)
    tr = SpanTracer(ring_size=16)
    outer = tr.span("outer", "serve")
    inner = tr.span("inner", "serve")
    outer.__enter__()
    inner.__enter__()
    outer.__exit__(None, None, None)  # parent closed before child: misnested
    assert reg.snapshot()["obs.misnested_spans"] == base + 1
    # the child is still tracked (it was not the misnested one)...
    assert [s["name"] for s in tr.open_spans()] == ["inner"]
    inner.__exit__(None, None, None)
    # ...and a clean close leaves nothing behind: no phantom open spans
    assert tr.open_spans() == []
    assert [e["name"] for e in _x_events(tr)] == ["outer", "inner"]
    assert reg.snapshot()["obs.misnested_spans"] == base + 1  # clean pop uncounted


def test_tracer_async_flow_events_and_thread_names():
    """Async (b/e) + flow (s/t/f) events carry the correlation id; registered
    worker threads get Perfetto thread_name metadata rows."""
    import threading

    tr = SpanTracer(ring_size=64)
    tr.async_begin("serve/request", 42, cls="interactive")
    tr.flow_start("serve/req", 42)

    def worker():
        tr.register_thread("serve-worker-x")
        tr.flow_step("serve/req", 42)
        tr.flow_end("serve/req", 42, outcome="completed")
        tr.async_end("serve/request", 42)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    doc = tr.to_chrome_trace()
    evts = doc["traceEvents"]
    corr = [e for e in evts if e.get("id") == 42]
    assert [e["ph"] for e in corr] == ["b", "s", "t", "f", "e"]
    assert len({e["tid"] for e in corr}) == 2  # two threads, one id
    flow_end = next(e for e in corr if e["ph"] == "f")
    assert flow_end["bp"] == "e" and flow_end["args"]["outcome"] == "completed"
    names = {e["args"]["name"] for e in evts if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "serve-worker-x" in names
    # disabled tracer: marks are no-ops
    off = SpanTracer(ring_size=4, enabled=False)
    off.async_begin("x", 1)
    off.register_thread("nope")
    assert [e for e in off.to_chrome_trace()["traceEvents"] if e.get("id")] == []


def test_tracer_module_singleton_configure():
    prev = obs_trace.get_tracer()
    try:
        tr = obs_trace.configure(enabled=True, ring_size=4)
        assert obs_trace.get_tracer() is tr
        with obs_trace.get_tracer().span("x", "data"):
            pass
        assert [e["name"] for e in _x_events(tr)] == ["x"]
    finally:
        obs_trace._TRACER = prev


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_injected_stall(tmp_path):
    """à la test_fault_injection: the loop stops beating mid-span, the
    watchdog must dump a hang report with open spans + registry snapshot."""
    tr = SpanTracer(ring_size=8)
    reg = MetricsRegistry()
    reg.counter("train.rebuilds").inc(3)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.25, poll_s=0.05, tracer=tr, registry=reg)
    wd.start()
    span = tr.span("dispatch/train_step", "dispatch")
    span.__enter__()  # a dispatch that never returns
    wd.arm(step=7)
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    span.__exit__(None, None, None)
    assert report_path.exists(), "watchdog never fired on a stalled loop"
    assert wd.fired
    rep = json.loads(report_path.read_text())
    assert rep["last_step"] == 7
    assert rep["last_phase"] == "step"
    assert rep["seconds_since_last_beat"] >= 0.25
    assert any(s["name"] == "dispatch/train_step" for s in rep["open_spans"])
    assert rep["registry"]["train.rebuilds"] == 3.0
    assert rep["threads"], "thread stacks missing from hang report"
    assert any("MainThread" in name for name in rep["threads"])


def test_watchdog_silent_on_healthy_loop(tmp_path):
    wd = StallWatchdog(str(tmp_path), deadline_s=0.5, poll_s=0.05)
    wd.start()
    for step in range(12):  # ~0.6 s of healthy 50ms steps
        wd.arm(step)
        time.sleep(0.05)
    wd.stop()
    assert not (tmp_path / "hang_report.json").exists()
    assert not wd.fired


def test_watchdog_rejects_nonpositive_deadline(tmp_path):
    with pytest.raises(ValueError, match="deadline"):
        StallWatchdog(str(tmp_path), deadline_s=0.0)


def test_watchdog_info_providers_reach_hang_report(tmp_path):
    """The serving extension: registered info providers (batcher threads,
    in-flight window, breaker state — cli/serve.py wires the real ones)
    land in hang_report.json, and a provider that raises contributes its
    error string instead of killing the report."""
    wd = StallWatchdog(
        str(tmp_path), deadline_s=0.2, poll_s=0.05,
        info_providers={"serving": lambda: {
            "batcher_threads": [{"name": "serve-collect", "alive": True}],
            "inflight": 2,
            "admission": {"breaker": "open"},
        }},
    )

    def broken():
        raise RuntimeError("provider died")

    wd.register_info("broken", broken)
    wd.start()
    wd.arm(step=1, phase="serve")
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    assert report_path.exists()
    rep = json.loads(report_path.read_text())
    assert rep["last_phase"] == "serve"
    serving = rep["info"]["serving"]
    assert serving["inflight"] == 2
    assert serving["batcher_threads"][0]["name"] == "serve-collect"
    assert serving["admission"]["breaker"] == "open"
    assert "provider failed" in rep["info"]["broken"] and "provider died" in rep["info"]["broken"]


def test_watchdog_serving_report_from_live_batcher(tmp_path):
    """End-to-end serving hang report: a pipelined batcher wedged on a hung
    engine, the watchdog's serving section carries the real thread names,
    window occupancy, and breaker state."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.cli.serve import _serving_info
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    class _Echo:
        def predict_async(self, images):
            class _H:
                def result(_s):
                    return images[:, 0, 0, :1]
            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    eng = FaultyEngine(_Echo(), hang_at=0)
    b = PipelinedBatcher(eng, max_batch=1, max_wait_ms=0.0, drain_timeout_s=1.0).start()
    ac = AdmissionController(b)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.2, poll_s=0.05)
    wd.register_info("serving", lambda: _serving_info(b, ac))
    wd.start()
    wd.arm(phase="serve")
    try:
        fut = ac.submit(np.zeros((4, 4, 3), np.float32))
        report_path = tmp_path / "hang_report.json"
        deadline = time.time() + 10
        while time.time() < deadline and not report_path.exists():
            time.sleep(0.05)
        assert report_path.exists()
        rep = json.loads(report_path.read_text())
        serving = rep["info"]["serving"]
        names = {t["name"] for t in serving["batcher_threads"]}
        assert names == {"serve-collect", "serve-complete"}
        assert serving["inflight"] >= 1  # the wedged batch occupies the window
        assert serving["admission"]["breaker"] == "closed"
        assert serving["admission"]["classes"]["interactive"]["in_queue"] >= 1
        # the report NAMES the wedged request: id, class, age, phase
        oldest = serving["oldest_request"]
        assert oldest is not None
        assert oldest["class"] == "interactive"
        assert oldest["age_s"] >= 0.0 and oldest["id"] >= 1
        assert oldest["phase"] in ("queued", "dispatched")
        # the wedged request is also visible in the dumped thread stacks
        assert any("serve-complete" in name for name in rep["threads"])
    finally:
        wd.stop()
        b.stop()  # drain-bounded: the hung engine cannot wedge teardown
        with pytest.raises(Exception):
            fut.result(timeout=1)


# ---------------------------------------------------------------------------
# Logger integration
# ---------------------------------------------------------------------------


def test_logger_degrades_without_tensorflow(tmp_path, monkeypatch, capsys):
    """The satellite fix: tensorboard=True on a TF-less box must warn once
    and keep jsonl logging, not crash the run."""
    monkeypatch.setitem(__import__("sys").modules, "tensorflow", None)
    monkeypatch.setattr(logging_lib, "_TB_WARNED", False)
    log = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=True)
    try:
        assert log._tb is None
        out = capsys.readouterr().out
        assert "tensorboard logging disabled" in out
        # warn once only
        log2 = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=True)
        log2.close()
        assert "tensorboard logging disabled" not in capsys.readouterr().out
        log.scalars(3, {"loss": 1.5}, "train/")
    finally:
        log.close()
    rows = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert rows == [{"step": 3, "train/loss": 1.5}]


def test_logger_scalars_carry_registry_snapshot(tmp_path):
    reg = MetricsRegistry()
    reg.counter("data.decode_failures").inc(2)
    log = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=False)
    try:
        log.set_registry(reg)
        log.scalars(1, {"loss": 0.5}, "train/")
    finally:
        log.close()
    row = json.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
    assert row["train/loss"] == 0.5
    assert row["obs/data.decode_failures"] == 2.0


def test_emit_routes_through_active_logger(capsys):
    log = logging_lib.Logger(None, enabled=True)
    logging_lib.emit("hello from the pipeline")
    out = capsys.readouterr().out
    assert "] hello from the pipeline" in out  # Logger's [HH:MM:SS] prefix
    log.close()
    logging_lib.emit("after close")
    assert capsys.readouterr().out == "after close\n"  # bare fallback


# ---------------------------------------------------------------------------
# fake-data CPU train smoke: trace + snapshot artifacts
# ---------------------------------------------------------------------------


def _smoke_cfg(tmp_path, k_dispatch):
    return config_from_dict({
        "name": "obs-smoke",
        "model": {
            "arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
            "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}],
        },
        "data": {"dataset": "fake", "image_size": 24, "fake_train_size": 64, "fake_eval_size": 16},
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.01, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {
            "batch_size": 32, "eval_batch_size": 16, "epochs": 1, "log_every": 1,
            "compute_dtype": "float32", "log_dir": str(tmp_path),
            "steps_per_dispatch": k_dispatch,
        },
        # trace on; generous watchdog deadline proves it stays silent on a
        # healthy loop even with compiles in the gap
        "obs": {"trace": True, "watchdog_deadline_s": 300.0},
        "dist": {"num_devices": 8},
    })


@pytest.mark.parametrize("k_dispatch", [1, 2])
def test_train_smoke_emits_trace_and_registry_snapshot(tmp_path, k_dispatch):
    result = cli_train.run(_smoke_cfg(tmp_path, k_dispatch))
    assert result["epoch"] == pytest.approx(1.0)

    # valid Chrome-trace JSON with spans from all five core categories
    doc = json.loads((tmp_path / "obs_trace.json").read_text())
    evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evts, "no spans recorded"
    for e in evts:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    cats = {e["cat"] for e in evts}
    assert {"data", "dispatch", "sync", "eval", "ckpt"} <= cats, cats
    names = {e["name"] for e in evts}
    if k_dispatch > 1:
        # spans COMPOSE with grouped dispatch instead of forcing it off
        assert "dispatch/grouped_step" in names
        grouped = next(e for e in evts if e["name"] == "dispatch/grouped_step")
        assert grouped["args"]["steps"] == k_dispatch
    else:
        assert "dispatch/train_step" in names

    # registry snapshot written at run end
    snap = json.loads((tmp_path / "obs_registry.json").read_text())
    assert snap.get("ckpt.saves", 0) >= 1
    assert snap.get("eval.passes", 0) >= 1
    assert "ckpt.wait_seconds.count" in snap

    # every scalars row carries the obs/ snapshot
    rows = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert rows and all(any(k.startswith("obs/") for k in r) for r in rows)

    # healthy loop: armed watchdog stayed silent
    assert not (tmp_path / "hang_report.json").exists()


# ---------------------------------------------------------------------------
# scripts/obs_report.py
# ---------------------------------------------------------------------------


def _obs_report_mod():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_summary(tmp_path, capsys):
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "train/loss": 2.0, "train/images_per_sec": 100.0,
                    "obs/ckpt.saves": 0.0}) + "\n"
        + json.dumps({"step": 2, "eval/top1": 0.75, "eval/loss": 1.1}) + "\n"
    )
    (tmp_path / "obs_registry.json").write_text(
        json.dumps({"ckpt.saves": 1.0, "train.rebuilds": 2.0}))
    (tmp_path / "hang_report.json").write_text(json.dumps({
        "seconds_since_last_beat": 12.5, "deadline_s": 5.0, "last_step": 42,
        "last_phase": "step",
        "open_spans": [{"name": "dispatch/train_step", "cat": "dispatch", "open_for_s": 12.0}],
        "registry": {}, "threads": {"MainThread-1": ["..."]},
    }))
    rc = _obs_report_mod().main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "last train/loss = 2" in out
    assert "best eval/top1 = 0.75" in out
    assert "ckpt.saves = 1" in out
    assert "HANG REPORT" in out
    assert "dispatch/train_step" in out


def test_obs_report_device_section(tmp_path, capsys):
    """The device-telemetry section: compile events, per-executable cost,
    dispatch efficiency, memory gauges (obs/device.py surfaces)."""
    (tmp_path / "obs_registry.json").write_text(json.dumps({
        "obs.compiles": 3.0, "obs.compile_seconds.p50": 1.5,
        "obs.compile_seconds.max": 2.0, "obs.compile_seconds.sum": 4.0,
        "obs.cost_flops.serve_b8_s224_k1": 1.2e9,
        "obs.cost_bytes.serve_b8_s224_k1": 3.4e8,
        "serve.achieved_flops_per_s": 2.5e9, "serve.run_seconds.count": 4.0,
        "host.rss_bytes": 5e8, "device.live_buffer_bytes": 1e7,
        "device.bytes_in_use.d0": 2e9, "device.peak_bytes_in_use.d0": 3e9,
    }))
    rc = _obs_report_mod().main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "## device (compile / cost / memory)" in out
    assert "compiles = 3" in out and "p50 1.50s" in out
    assert "[serve_b8_s224_k1] 1.200 GFLOP, 340.0 MB accessed" in out
    assert "dispatch efficiency: 2.50 achieved GFLOP/s" in out
    assert "host rss 500 MB" in out
    assert "d0 in-use 2000 MB (peak 3000)" in out


def test_obs_report_missing_dir(capsys):
    assert _obs_report_mod().main(["/definitely/not/a/dir"]) == 2


def test_obs_report_requests_waterfalls_and_quantiles(tmp_path, capsys):
    """--requests renders per-request waterfalls from the trace's async
    events and a per-phase quantile table from the registry snapshot."""
    us = 1000.0  # µs timestamps in the trace
    events = [
        # request 17: queued 2 ms, in-flight 3 ms, across two threads
        {"name": "serve/request", "ph": "b", "id": 17, "tid": 1, "ts": 0,
         "args": {"cls": "interactive", "deadline_ms": 50.0}},
        {"name": "serve/queued", "ph": "b", "id": 17, "tid": 1, "ts": 0},
        {"name": "serve/queued", "ph": "e", "id": 17, "tid": 2, "ts": 2 * us},
        {"name": "serve/inflight", "ph": "b", "id": 17, "tid": 2, "ts": 2 * us},
        {"name": "serve/inflight", "ph": "e", "id": 17, "tid": 3, "ts": 5 * us},
        {"name": "serve/request", "ph": "e", "id": 17, "tid": 3, "ts": 5.2 * us,
         "args": {"outcome": "completed"}},
        # a flow step rides along and must not confuse the waterfall parse
        {"name": "serve/req", "ph": "t", "id": 17, "tid": 2, "ts": 2 * us},
    ]
    (tmp_path / "obs_trace.json").write_text(json.dumps({"traceEvents": events}))
    (tmp_path / "obs_registry.json").write_text(json.dumps({
        "serve.queue_wait_seconds.count": 4.0,
        "serve.queue_wait_seconds.p50": 0.002, "serve.queue_wait_seconds.p95": 0.003,
        "serve.queue_wait_seconds.p99": 0.0031, "serve.queue_wait_seconds.min": 0.001,
        "serve.queue_wait_seconds.max": 0.0032,
        "serve.latency_seconds.interactive.count": 4.0,
        "serve.latency_seconds.interactive.p50": 0.005,
        "serve.latency_seconds.interactive.p99": 0.009,
    }))
    rc = _obs_report_mod().main([str(tmp_path), "--requests"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "#17" in out and "class=interactive" in out
    assert "total=5.20ms" in out and "queued=2.00ms" in out and "inflight=3.00ms" in out
    assert "[completed]" in out
    assert "queue wait" in out and "latency [interactive]" in out
    assert "p50_ms" in out and "p99_ms" in out
