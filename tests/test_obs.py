"""obs/ subsystem tests: registry semantics, span tracer (nesting, ring
eviction, Chrome-trace schema), stall watchdog (fires on an injected stall,
silent on a healthy loop), Logger integration (TF-less degrade, registry
snapshots in scalars rows), the fake-data train smoke (trace + snapshot
artifacts for steps_per_dispatch 1 and >1), and scripts/obs_report.py."""

import importlib.util
import json
import os
import time

import pytest

from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.obs.registry import MetricsRegistry, get_registry
from yet_another_mobilenet_series_tpu.obs.trace import SpanTracer
from yet_another_mobilenet_series_tpu.obs import trace as obs_trace
from yet_another_mobilenet_series_tpu.obs.watchdog import StallWatchdog
from yet_another_mobilenet_series_tpu.utils import logging as logging_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("a.hits").inc()
    reg.counter("a.hits").inc(2)
    reg.gauge("a.level").set(7.5)
    h = reg.histogram("a.wait")
    h.observe(1.0)
    h.observe(3.0)
    snap = reg.snapshot()
    assert snap["a.hits"] == 3.0
    assert snap["a.level"] == 7.5
    assert snap["a.wait.count"] == 2.0
    assert snap["a.wait.sum"] == 4.0
    assert snap["a.wait.mean"] == 2.0
    assert snap["a.wait.max"] == 3.0
    # get-or-create returns the SAME metric object
    assert reg.counter("a.hits") is reg.counter("a.hits")


def test_registry_type_conflict_and_negative_inc():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")
    with pytest.raises(ValueError, match="cannot decrease"):
        reg.counter("x").inc(-1)


def test_registry_gauge_callback_and_fault_isolation():
    reg = MetricsRegistry()
    src = {"v": 5}
    g = reg.gauge("pull")
    g.set_fn(lambda: src["v"])
    assert reg.snapshot()["pull"] == 5.0
    src["v"] = 9
    assert reg.snapshot()["pull"] == 9.0
    # a dying producer keeps the last good reading, never raises
    g.set_fn(lambda: 1 / 0)
    assert reg.snapshot()["pull"] == 9.0


def test_registry_reset():
    reg = MetricsRegistry()
    reg.counter("x").inc()
    reg.reset()
    assert reg.snapshot() == {}


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def _x_events(tracer):
    return [e for e in tracer.to_chrome_trace()["traceEvents"] if e["ph"] == "X"]


def test_tracer_span_nesting_and_containment():
    tr = SpanTracer(ring_size=16)
    with tr.span("outer", "dispatch", steps=2):
        with tr.span("inner", "sync"):
            time.sleep(0.001)
    evts = _x_events(tr)
    # completion order: inner closes first
    assert [e["name"] for e in evts] == ["inner", "outer"]
    inner, outer = evts
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["args"] == {"steps": 2}


def test_tracer_ring_eviction():
    tr = SpanTracer(ring_size=4)
    for i in range(10):
        with tr.span(f"s{i}", "data"):
            pass
    evts = _x_events(tr)
    assert [e["name"] for e in evts] == ["s6", "s7", "s8", "s9"]


def test_tracer_chrome_trace_schema(tmp_path):
    tr = SpanTracer(ring_size=8)
    with tr.span("a", "data"):
        pass
    path = tr.write(str(tmp_path / "trace.json"))
    doc = json.loads(open(path).read())
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M")
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["cat"], str)


def test_tracer_disabled_is_noop():
    tr = SpanTracer(ring_size=8, enabled=False)
    s1 = tr.span("a", "data")
    s2 = tr.span("b", "sync")
    assert s1 is s2  # the shared null span: zero allocation on the hot path
    with s1:
        pass
    assert _x_events(tr) == []


def test_tracer_open_spans_readout():
    tr = SpanTracer(ring_size=8)
    with tr.span("outer", "dispatch"):
        with tr.span("inner", "data"):
            open_now = tr.open_spans()
            assert [s["name"] for s in open_now] == ["outer", "inner"]
            assert all(s["open_for_s"] >= 0 for s in open_now)
    assert tr.open_spans() == []


def test_tracer_module_singleton_configure():
    prev = obs_trace.get_tracer()
    try:
        tr = obs_trace.configure(enabled=True, ring_size=4)
        assert obs_trace.get_tracer() is tr
        with obs_trace.get_tracer().span("x", "data"):
            pass
        assert [e["name"] for e in _x_events(tr)] == ["x"]
    finally:
        obs_trace._TRACER = prev


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_injected_stall(tmp_path):
    """à la test_fault_injection: the loop stops beating mid-span, the
    watchdog must dump a hang report with open spans + registry snapshot."""
    tr = SpanTracer(ring_size=8)
    reg = MetricsRegistry()
    reg.counter("train.rebuilds").inc(3)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.25, poll_s=0.05, tracer=tr, registry=reg)
    wd.start()
    span = tr.span("dispatch/train_step", "dispatch")
    span.__enter__()  # a dispatch that never returns
    wd.arm(step=7)
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    span.__exit__(None, None, None)
    assert report_path.exists(), "watchdog never fired on a stalled loop"
    assert wd.fired
    rep = json.loads(report_path.read_text())
    assert rep["last_step"] == 7
    assert rep["last_phase"] == "step"
    assert rep["seconds_since_last_beat"] >= 0.25
    assert any(s["name"] == "dispatch/train_step" for s in rep["open_spans"])
    assert rep["registry"]["train.rebuilds"] == 3.0
    assert rep["threads"], "thread stacks missing from hang report"
    assert any("MainThread" in name for name in rep["threads"])


def test_watchdog_silent_on_healthy_loop(tmp_path):
    wd = StallWatchdog(str(tmp_path), deadline_s=0.5, poll_s=0.05)
    wd.start()
    for step in range(12):  # ~0.6 s of healthy 50ms steps
        wd.arm(step)
        time.sleep(0.05)
    wd.stop()
    assert not (tmp_path / "hang_report.json").exists()
    assert not wd.fired


def test_watchdog_rejects_nonpositive_deadline(tmp_path):
    with pytest.raises(ValueError, match="deadline"):
        StallWatchdog(str(tmp_path), deadline_s=0.0)


def test_watchdog_info_providers_reach_hang_report(tmp_path):
    """The serving extension: registered info providers (batcher threads,
    in-flight window, breaker state — cli/serve.py wires the real ones)
    land in hang_report.json, and a provider that raises contributes its
    error string instead of killing the report."""
    wd = StallWatchdog(
        str(tmp_path), deadline_s=0.2, poll_s=0.05,
        info_providers={"serving": lambda: {
            "batcher_threads": [{"name": "serve-collect", "alive": True}],
            "inflight": 2,
            "admission": {"breaker": "open"},
        }},
    )

    def broken():
        raise RuntimeError("provider died")

    wd.register_info("broken", broken)
    wd.start()
    wd.arm(step=1, phase="serve")
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    assert report_path.exists()
    rep = json.loads(report_path.read_text())
    assert rep["last_phase"] == "serve"
    serving = rep["info"]["serving"]
    assert serving["inflight"] == 2
    assert serving["batcher_threads"][0]["name"] == "serve-collect"
    assert serving["admission"]["breaker"] == "open"
    assert "provider failed" in rep["info"]["broken"] and "provider died" in rep["info"]["broken"]


def test_watchdog_serving_report_from_live_batcher(tmp_path):
    """End-to-end serving hang report: a pipelined batcher wedged on a hung
    engine, the watchdog's serving section carries the real thread names,
    window occupancy, and breaker state."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.cli.serve import _serving_info
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    class _Echo:
        def predict_async(self, images):
            class _H:
                def result(_s):
                    return images[:, 0, 0, :1]
            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    eng = FaultyEngine(_Echo(), hang_at=0)
    b = PipelinedBatcher(eng, max_batch=1, max_wait_ms=0.0, drain_timeout_s=1.0).start()
    ac = AdmissionController(b)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.2, poll_s=0.05)
    wd.register_info("serving", lambda: _serving_info(b, ac))
    wd.start()
    wd.arm(phase="serve")
    try:
        fut = ac.submit(np.zeros((4, 4, 3), np.float32))
        report_path = tmp_path / "hang_report.json"
        deadline = time.time() + 10
        while time.time() < deadline and not report_path.exists():
            time.sleep(0.05)
        assert report_path.exists()
        rep = json.loads(report_path.read_text())
        serving = rep["info"]["serving"]
        names = {t["name"] for t in serving["batcher_threads"]}
        assert names == {"serve-collect", "serve-complete"}
        assert serving["inflight"] >= 1  # the wedged batch occupies the window
        assert serving["admission"]["breaker"] == "closed"
        assert serving["admission"]["classes"]["interactive"]["in_queue"] >= 1
        # the wedged request is also visible in the dumped thread stacks
        assert any("serve-complete" in name for name in rep["threads"])
    finally:
        wd.stop()
        b.stop()  # drain-bounded: the hung engine cannot wedge teardown
        with pytest.raises(Exception):
            fut.result(timeout=1)


# ---------------------------------------------------------------------------
# Logger integration
# ---------------------------------------------------------------------------


def test_logger_degrades_without_tensorflow(tmp_path, monkeypatch, capsys):
    """The satellite fix: tensorboard=True on a TF-less box must warn once
    and keep jsonl logging, not crash the run."""
    monkeypatch.setitem(__import__("sys").modules, "tensorflow", None)
    monkeypatch.setattr(logging_lib, "_TB_WARNED", False)
    log = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=True)
    try:
        assert log._tb is None
        out = capsys.readouterr().out
        assert "tensorboard logging disabled" in out
        # warn once only
        log2 = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=True)
        log2.close()
        assert "tensorboard logging disabled" not in capsys.readouterr().out
        log.scalars(3, {"loss": 1.5}, "train/")
    finally:
        log.close()
    rows = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert rows == [{"step": 3, "train/loss": 1.5}]


def test_logger_scalars_carry_registry_snapshot(tmp_path):
    reg = MetricsRegistry()
    reg.counter("data.decode_failures").inc(2)
    log = logging_lib.Logger(str(tmp_path), enabled=True, tensorboard=False)
    try:
        log.set_registry(reg)
        log.scalars(1, {"loss": 0.5}, "train/")
    finally:
        log.close()
    row = json.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
    assert row["train/loss"] == 0.5
    assert row["obs/data.decode_failures"] == 2.0


def test_emit_routes_through_active_logger(capsys):
    log = logging_lib.Logger(None, enabled=True)
    logging_lib.emit("hello from the pipeline")
    out = capsys.readouterr().out
    assert "] hello from the pipeline" in out  # Logger's [HH:MM:SS] prefix
    log.close()
    logging_lib.emit("after close")
    assert capsys.readouterr().out == "after close\n"  # bare fallback


# ---------------------------------------------------------------------------
# fake-data CPU train smoke: trace + snapshot artifacts
# ---------------------------------------------------------------------------


def _smoke_cfg(tmp_path, k_dispatch):
    return config_from_dict({
        "name": "obs-smoke",
        "model": {
            "arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
            "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}],
        },
        "data": {"dataset": "fake", "image_size": 24, "fake_train_size": 64, "fake_eval_size": 16},
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.01, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {
            "batch_size": 32, "eval_batch_size": 16, "epochs": 1, "log_every": 1,
            "compute_dtype": "float32", "log_dir": str(tmp_path),
            "steps_per_dispatch": k_dispatch,
        },
        # trace on; generous watchdog deadline proves it stays silent on a
        # healthy loop even with compiles in the gap
        "obs": {"trace": True, "watchdog_deadline_s": 300.0},
        "dist": {"num_devices": 8},
    })


@pytest.mark.parametrize("k_dispatch", [1, 2])
def test_train_smoke_emits_trace_and_registry_snapshot(tmp_path, k_dispatch):
    result = cli_train.run(_smoke_cfg(tmp_path, k_dispatch))
    assert result["epoch"] == pytest.approx(1.0)

    # valid Chrome-trace JSON with spans from all five core categories
    doc = json.loads((tmp_path / "obs_trace.json").read_text())
    evts = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evts, "no spans recorded"
    for e in evts:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    cats = {e["cat"] for e in evts}
    assert {"data", "dispatch", "sync", "eval", "ckpt"} <= cats, cats
    names = {e["name"] for e in evts}
    if k_dispatch > 1:
        # spans COMPOSE with grouped dispatch instead of forcing it off
        assert "dispatch/grouped_step" in names
        grouped = next(e for e in evts if e["name"] == "dispatch/grouped_step")
        assert grouped["args"]["steps"] == k_dispatch
    else:
        assert "dispatch/train_step" in names

    # registry snapshot written at run end
    snap = json.loads((tmp_path / "obs_registry.json").read_text())
    assert snap.get("ckpt.saves", 0) >= 1
    assert snap.get("eval.passes", 0) >= 1
    assert "ckpt.wait_seconds.count" in snap

    # every scalars row carries the obs/ snapshot
    rows = [json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert rows and all(any(k.startswith("obs/") for k in r) for r in rows)

    # healthy loop: armed watchdog stayed silent
    assert not (tmp_path / "hang_report.json").exists()


# ---------------------------------------------------------------------------
# scripts/obs_report.py
# ---------------------------------------------------------------------------


def _obs_report_mod():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_summary(tmp_path, capsys):
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "train/loss": 2.0, "train/images_per_sec": 100.0,
                    "obs/ckpt.saves": 0.0}) + "\n"
        + json.dumps({"step": 2, "eval/top1": 0.75, "eval/loss": 1.1}) + "\n"
    )
    (tmp_path / "obs_registry.json").write_text(
        json.dumps({"ckpt.saves": 1.0, "train.rebuilds": 2.0}))
    (tmp_path / "hang_report.json").write_text(json.dumps({
        "seconds_since_last_beat": 12.5, "deadline_s": 5.0, "last_step": 42,
        "last_phase": "step",
        "open_spans": [{"name": "dispatch/train_step", "cat": "dispatch", "open_for_s": 12.0}],
        "registry": {}, "threads": {"MainThread-1": ["..."]},
    }))
    rc = _obs_report_mod().main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "last train/loss = 2" in out
    assert "best eval/top1 = 0.75" in out
    assert "ckpt.saves = 1" in out
    assert "HANG REPORT" in out
    assert "dispatch/train_step" in out


def test_obs_report_missing_dir(capsys):
    assert _obs_report_mod().main(["/definitely/not/a/dir"]) == 2
