import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu import ops


def test_activation_values():
    x = jnp.array([-4.0, -3.0, -1.0, 0.0, 1.0, 3.0, 10.0])
    np.testing.assert_allclose(ops.relu6(x), np.clip(x, 0, 6))
    # h-swish = x*relu6(x+3)/6 (MobileNetV3 paper exact form)
    np.testing.assert_allclose(ops.hswish(x), x * np.clip(x + 3, 0, 6) / 6, rtol=1e-5)
    np.testing.assert_allclose(ops.hsigmoid(x), np.clip(x + 3, 0, 6) / 6, rtol=1e-5)
    np.testing.assert_allclose(ops.swish(x), x / (1 + np.exp(-x)), rtol=1e-5)
    assert ops.hswish(jnp.array(-3.0)) == 0.0
    assert ops.hswish(jnp.array(10.0)) == 10.0
    with pytest.raises(ValueError):
        ops.get_activation("nope")


def test_make_divisible():
    # Reference semantics: round to nearest multiple of 8, never below 90%.
    assert ops.make_divisible(32) == 32
    assert ops.make_divisible(32 * 0.75) == 24
    assert ops.make_divisible(33) == 32
    assert ops.make_divisible(39) == 40
    assert ops.make_divisible(91) == 88  # 88 >= 0.9*91
    assert ops.make_divisible(8 * 0.35) == 8  # min_value clamp
    assert ops.make_divisible(16, divisor=8, min_value=16) == 16


def _torch_conv(x_nhwc, w_hwio, stride, groups, pad):
    import torch
    import torch.nn.functional as F

    xt = torch.from_numpy(np.asarray(x_nhwc).transpose(0, 3, 1, 2)).double()
    # HWIO -> OIHW
    wt = torch.from_numpy(np.asarray(w_hwio).transpose(3, 2, 0, 1)).double()
    y = F.conv2d(xt, wt, stride=stride, padding=pad, groups=groups)
    return y.numpy().transpose(0, 2, 3, 1)


@pytest.mark.parametrize("cin,cout,k,stride,groups", [
    (8, 16, 3, 1, 1),
    (8, 16, 1, 1, 1),
    (16, 16, 3, 2, 16),   # depthwise stride 2
    (16, 16, 5, 1, 16),   # depthwise k=5
    (12, 24, 7, 2, 1),
])
def test_conv2d_matches_torch(cin, cout, k, stride, groups):
    torch = pytest.importorskip("torch")  # noqa: F841
    key = jax.random.PRNGKey(0)
    spec = ops.Conv2D(cin, cout, k, stride, groups)
    params = spec.init(key)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 13, 13, cin))
    y = spec.apply(params, x)
    y_ref = _torch_conv(x, params["w"], stride, groups, k // 2)
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-5)


def test_conv1x1_as_dot_matches_conv_lowering():
    """as_dot (the round-3 weight-grad MXU experiment, train.conv1x1_dot)
    must be a pure lowering change: forward values and weight gradients
    match the conv_general_dilated path, including the stride>1 subsample
    case; k>1 and grouped convs ignore the flag entirely."""
    for cin, cout, stride in [(8, 16, 1), (8, 16, 2), (16, 5, 1)]:
        spec = ops.Conv2D(cin, cout, 1, stride)
        params = spec.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 9, 9, cin))

        y_conv = spec.apply(params, x)
        y_dot = spec.apply(params, x, as_dot=True)
        np.testing.assert_allclose(np.asarray(y_dot), np.asarray(y_conv), rtol=1e-5, atol=1e-6)

        def loss(p, as_dot):
            return jnp.sum(jnp.square(spec.apply(p, x, as_dot=as_dot)))

        g_conv = jax.grad(loss)(params, False)["w"]
        g_dot = jax.grad(loss)(params, True)["w"]
        np.testing.assert_allclose(np.asarray(g_dot), np.asarray(g_conv), rtol=1e-4, atol=1e-5)

    # non-1x1 / grouped: flag is a no-op (same lowering, identical values)
    dw = ops.Conv2D(8, 8, 3, 1, groups=8)
    pdw = dw.init(jax.random.PRNGKey(2))
    xdw = jax.random.normal(jax.random.PRNGKey(3), (2, 7, 7, 8))
    np.testing.assert_array_equal(
        np.asarray(dw.apply(pdw, xdw, as_dot=True)), np.asarray(dw.apply(pdw, xdw))
    )


def test_batchnorm_matches_torch_train_and_eval():
    import torch

    c = 6
    spec = ops.BatchNorm(c, momentum=0.1, eps=1e-5)
    params, state = spec.init()
    # random gamma/beta to make the test non-trivial
    params["gamma"] = jnp.asarray(np.random.RandomState(0).uniform(0.5, 1.5, c).astype(np.float32))
    params["beta"] = jnp.asarray(np.random.RandomState(1).uniform(-0.5, 0.5, c).astype(np.float32))
    x = np.random.RandomState(2).normal(size=(4, 5, 5, c)).astype(np.float32)

    bn = torch.nn.BatchNorm2d(c, momentum=0.1, eps=1e-5)
    bn.weight.data = torch.from_numpy(np.asarray(params["gamma"]))
    bn.bias.data = torch.from_numpy(np.asarray(params["beta"]))
    xt = torch.from_numpy(x.transpose(0, 3, 1, 2))

    # train step: normalized output + running-stat update semantics
    y, new_state = spec.apply(params, state, jnp.asarray(x), train=True)
    bn.train()
    yt = bn(xt).detach().numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), yt, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state["mean"]), bn.running_mean.numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_state["var"]), bn.running_var.numpy(), rtol=1e-5, atol=1e-6)

    # eval uses running stats
    y_eval, same_state = spec.apply(params, new_state, jnp.asarray(x), train=False)
    bn.eval()
    yt_eval = bn(xt).detach().numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y_eval), yt_eval, rtol=1e-4, atol=1e-5)
    assert same_state is new_state


def test_batchnorm_modes_equivalent():
    """The bn_mode perf variants (ops/layers.py; the round-2 trace's 52%
    BN-reduction attack) must be semantics-preserving: statistics bit-exact
    in every mode; "folded" normalize within f32 re-association rounding of
    "exact"; "compute" within bf16 tolerance on bf16 inputs."""
    c = 12
    spec = ops.BatchNorm(c)
    params, state = spec.init()
    rs = np.random.RandomState(0)
    params["gamma"] = jnp.asarray(rs.uniform(0.5, 1.5, c).astype(np.float32))
    params["beta"] = jnp.asarray(rs.uniform(-0.5, 0.5, c).astype(np.float32))
    x = jnp.asarray(rs.normal(2.0, 3.0, (8, 7, 7, c)).astype(np.float32))

    for train in (True, False):
        y_exact, st_exact = spec.apply(params, state, x, train=train, mode="exact")
        y_folded, st_folded = spec.apply(params, state, x, train=train, mode="folded")
        y_compute, st_compute = spec.apply(params, state, x, train=train, mode="compute")
        for st in (st_folded, st_compute):
            for k in ("mean", "var"):
                np.testing.assert_array_equal(np.asarray(st[k]), np.asarray(st_exact[k]))
        np.testing.assert_allclose(np.asarray(y_folded), np.asarray(y_exact), rtol=2e-6, atol=2e-6)
        np.testing.assert_allclose(np.asarray(y_compute), np.asarray(y_exact), rtol=2e-2, atol=2e-2)

    # bf16 activations (the real training dtype): folded stays within one
    # bf16 ulp of exact after the output cast; gradients agree too.
    xb = x.astype(jnp.bfloat16)
    yb_exact, _ = spec.apply(params, state, xb, train=True, mode="exact")
    yb_folded, _ = spec.apply(params, state, xb, train=True, mode="folded")
    yb_compute, _ = spec.apply(params, state, xb, train=True, mode="compute")
    np.testing.assert_allclose(
        np.asarray(yb_folded, np.float32), np.asarray(yb_exact, np.float32), rtol=1e-2, atol=1e-2
    )
    np.testing.assert_allclose(
        np.asarray(yb_compute, np.float32), np.asarray(yb_exact, np.float32), rtol=4e-2, atol=4e-2
    )

    def loss(p, mode):
        y, _ = spec.apply(p, state, x, train=True, mode=mode)
        return jnp.sum(jnp.square(y) * jnp.cos(jnp.arange(y.size).reshape(y.shape)))

    g_exact = jax.grad(loss)(params, "exact")
    g_folded = jax.grad(loss)(params, "folded")
    for k in ("gamma", "beta"):
        np.testing.assert_allclose(np.asarray(g_folded[k]), np.asarray(g_exact[k]), rtol=1e-4, atol=1e-4)

    with pytest.raises(ValueError):
        spec.apply(params, state, x, train=True, mode="nope")


def test_batchnorm_fused_vjp_matches_autodiff():
    """mode='fused_vjp': forward values equal 'folded' bit-for-bit, running
    stats equal every other mode's, and the closed-form backward reproduces
    autodiff-through-the-moments gradients for x, gamma, AND beta."""
    c = 12
    spec = ops.BatchNorm(c)
    params, state = spec.init()
    rs = np.random.RandomState(3)
    params["gamma"] = jnp.asarray(rs.uniform(0.5, 1.5, c).astype(np.float32))
    params["beta"] = jnp.asarray(rs.uniform(-0.5, 0.5, c).astype(np.float32))
    x = jnp.asarray(rs.normal(1.0, 2.0, (8, 7, 7, c)).astype(np.float32))

    y_folded, st_folded = spec.apply(params, state, x, train=True, mode="folded")
    y_fused, st_fused = spec.apply(params, state, x, train=True, mode="fused_vjp")
    np.testing.assert_array_equal(np.asarray(y_fused), np.asarray(y_folded))
    for k in ("mean", "var"):
        np.testing.assert_allclose(np.asarray(st_fused[k]), np.asarray(st_folded[k]), rtol=1e-6)

    w = jnp.asarray(rs.normal(0, 1, (8, 7, 7, c)).astype(np.float32))

    def loss(p, xx, mode):
        y, _ = spec.apply(p, state, xx, train=True, mode=mode)
        return jnp.sum(y * w)  # non-trivial cotangent

    (g_exact, gx_exact) = jax.grad(loss, argnums=(0, 1))(params, x, "exact")
    (g_fused, gx_fused) = jax.grad(loss, argnums=(0, 1))(params, x, "fused_vjp")
    np.testing.assert_allclose(np.asarray(gx_fused), np.asarray(gx_exact), rtol=1e-4, atol=1e-5)
    for k in ("gamma", "beta"):
        np.testing.assert_allclose(np.asarray(g_fused[k]), np.asarray(g_exact[k]), rtol=1e-4, atol=1e-5)

    # eval mode falls back to the folded expression (no custom vjp needed)
    y_eval_fused, _ = spec.apply(params, st_fused, x, train=False, mode="fused_vjp")
    y_eval_folded, _ = spec.apply(params, st_folded, x, train=False, mode="folded")
    np.testing.assert_array_equal(np.asarray(y_eval_fused), np.asarray(y_eval_folded))


def test_batchnorm_fused_vjp_rejects_stat_cotangents():
    """ADVICE r3 #1: the closed-form backward DISCARDS the mean/var output
    cotangents by contract (they feed only the never-differentiated running
    stats). With symbolic_zeros enforcement, a loss term that reads the
    batch statistics must fail LOUDLY at trace time under fused_vjp rather
    than silently training with zero stat-gradients."""
    spec = ops.BatchNorm(4)
    params, state = spec.init()
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (2, 3, 3, 4)).astype(np.float32))

    def stat_loss(p):
        _, st = spec.apply(p, state, x, train=True, mode="fused_vjp")
        return jnp.sum(st["mean"])  # differentiates the batch statistics

    with pytest.raises(TypeError, match="fused_vjp.*cotangents"):
        jax.grad(stat_loss)(params)

    # the same loss is fine under the autodiff modes
    def stat_loss_folded(p):
        _, st = spec.apply(p, state, x, train=True, mode="folded")
        return jnp.sum(st["mean"])

    g = jax.grad(stat_loss_folded)(params)
    assert all(np.all(np.isfinite(np.asarray(v))) for v in g.values())


def test_batchnorm_fused_vjp_sharded_grad_contract_matches_exact():
    """The per-device gradient CONTRACT under shard_map: fused_vjp's custom
    backward must produce the same per-device partial gradients of the LOCAL
    loss that autodiff of 'exact' produces (local dγ/dβ sums, global n) —
    the convention train/steps.py's grad pmean (and the ZeRO psum_scatter)
    assumes for every mode. A psum'd dγ/dβ inside the custom bwd would pass
    a globally-normalized comparison but train BN affine params at
    device_count× the gradient through the real step (caught by review in
    round 3; this test pins the seam per-device, no normalization games).

    check_vma=False deliberately matches parallel/dp.py's shard_maps: under
    the new vma semantics the cotangent of a replicated param is auto-psum'd
    OUTSIDE a custom_vjp's view, so fused_vjp is only contract-correct in
    check_vma=False contexts — which is what every production shard_map in
    this codebase uses (documented in ops/layers.py)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from yet_another_mobilenet_series_tpu.utils.compat import shard_map

    c = 4
    spec = ops.BatchNorm(c)
    params, state = spec.init()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, c))
    w = jax.random.normal(jax.random.PRNGKey(1), (16, 3, 3, c))
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    def per_device_grads(mode):
        def local_loss(p, xx, ww):
            y, _ = spec.apply(p, state, xx, train=True, axis_name="data", mode=mode)
            return jnp.sum(y * ww)

        def body(p, xx, ww):
            g, gx = jax.grad(local_loss, argnums=(0, 1))(p, xx, ww)
            # return the RAW per-device partials, laid out on the data axis,
            # so the contract is compared device by device
            return jax.tree.map(lambda v: v[None], g), gx

        return jax.jit(
            shard_map(body, mesh=mesh, in_specs=(P(), P("data"), P("data")),
                      out_specs=(P("data"), P("data")), check_vma=False)
        )(params, x, w)

    g_exact, gx_exact = per_device_grads("exact")
    g_fused, gx_fused = per_device_grads("fused_vjp")
    for k in ("gamma", "beta"):
        assert g_fused[k].shape == (8, c)  # one partial per device
        np.testing.assert_allclose(np.asarray(g_fused[k]), np.asarray(g_exact[k]), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx_fused), np.asarray(gx_exact), rtol=1e-4, atol=1e-5)


def test_batchnorm_sdot_stats_match_reduce():
    """mode='sdot' (MXU-dot batch statistics, the round-4 A/B candidate):
    values, gradients, and running stats must match 'folded' (identical
    normalize expression) within f32 accumulation-order rounding — the one
    mode whose statistics are NOT bit-identical to the reduce-based ones,
    by construction."""
    c = 12
    spec = ops.BatchNorm(c)
    params, state = spec.init()
    rs = np.random.RandomState(7)
    params["gamma"] = jnp.asarray(rs.uniform(0.5, 1.5, c).astype(np.float32))
    params["beta"] = jnp.asarray(rs.uniform(-0.5, 0.5, c).astype(np.float32))
    x = jnp.asarray(rs.normal(1.0, 2.0, (8, 7, 7, c)).astype(np.float32))

    y_ref, st_ref = spec.apply(params, state, x, train=True, mode="folded")
    y_dot, st_dot = spec.apply(params, state, x, train=True, mode="sdot")
    np.testing.assert_allclose(np.asarray(y_dot), np.asarray(y_ref), rtol=1e-5, atol=1e-5)
    for k in ("mean", "var"):
        np.testing.assert_allclose(np.asarray(st_dot[k]), np.asarray(st_ref[k]), rtol=1e-5, atol=1e-6)

    w = jnp.asarray(rs.normal(0, 1, (8, 7, 7, c)).astype(np.float32))

    def loss(p, xx, mode):
        y, _ = spec.apply(p, state, xx, train=True, mode=mode)
        return jnp.sum(y * w)

    (g_ref, gx_ref) = jax.grad(loss, argnums=(0, 1))(params, x, "folded")
    (g_dot, gx_dot) = jax.grad(loss, argnums=(0, 1))(params, x, "sdot")
    np.testing.assert_allclose(np.asarray(gx_dot), np.asarray(gx_ref), rtol=1e-4, atol=1e-5)
    for k in ("gamma", "beta"):
        np.testing.assert_allclose(np.asarray(g_dot[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5)

    # bf16 activations (the real training dtype): the dot's bf16 products
    # are exact in the f32 accumulator, so stats stay at f32-rounding
    # distance even from bf16 inputs
    xb = x.astype(jnp.bfloat16)
    _, st_b16 = spec.apply(params, state, xb, train=True, mode="sdot")
    _, st_ref16 = spec.apply(params, state, xb, train=True, mode="folded")
    for k in ("mean", "var"):
        np.testing.assert_allclose(np.asarray(st_b16[k]), np.asarray(st_ref16[k]), rtol=1e-5, atol=1e-6)

    # eval mode uses running stats: sdot is folded exactly
    y_eval_dot, _ = spec.apply(params, st_dot, x, train=False, mode="sdot")
    y_eval_folded, _ = spec.apply(params, st_dot, x, train=False, mode="folded")
    np.testing.assert_array_equal(np.asarray(y_eval_dot), np.asarray(y_eval_folded))


@pytest.mark.parametrize("mode", ["exact", "folded", "compute", "fused_vjp", "sdot", "compute_sdot"])
def test_syncbn_equals_full_batch_bn(mode):
    """psum-of-moments SyncBN over 8 shards == BN over the unsharded batch
    (SURVEY.md §4.2) — the apex-SyncBatchNorm parity contract, in every
    bn_mode normalize variant."""
    from jax.sharding import Mesh, PartitionSpec as P

    from yet_another_mobilenet_series_tpu.utils.compat import shard_map

    c = 4
    spec = ops.BatchNorm(c)
    params, state = spec.init()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, c))

    y_ref, st_ref = spec.apply(params, state, x, train=True, mode=mode)

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    def shard_fn(p, s, xx):
        return spec.apply(p, s, xx, train=True, axis_name="data", mode=mode)

    y, st = jax.jit(
        shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P("data"), P()),
            # matches every production shard_map (parallel/dp.py): the
            # fused_vjp custom backward has no replication rule, and old-jax
            # check_rep=True rejects it outright (NotImplementedError)
            check_vma=False,
        )
    )(params, state, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st["mean"]), np.asarray(st_ref["mean"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(st["var"]), np.asarray(st_ref["var"]), rtol=1e-5, atol=1e-6)


def test_inverted_residual_shapes_and_residual():
    spec = ops.InvertedResidual(
        in_channels=16, out_channels=16, expanded_channels=48, stride=1,
        kernel_sizes=(3, 5, 7), group_channels=(16, 16, 16), active_fn="hswish", se_channels=12,
    )
    params, state = spec.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 16))
    y, new_state = spec.apply(params, state, x, train=True)
    assert y.shape == (2, 8, 8, 16)
    assert spec.has_residual
    # stride-2 block: no residual, spatial halved
    spec2 = ops.InvertedResidual(16, 24, 96, stride=2, kernel_sizes=(3,))
    p2, s2 = spec2.init(jax.random.PRNGKey(2))
    y2, _ = spec2.apply(p2, s2, x, train=False)
    assert y2.shape == (2, 4, 4, 24)
    assert not spec2.has_residual


def test_inverted_residual_no_expand_when_t1():
    spec = ops.InvertedResidual(16, 16, 16, stride=1, kernel_sizes=(3,))
    params, _ = spec.init(jax.random.PRNGKey(0))
    assert "expand" not in params and not spec.has_expand


def test_inverted_residual_validation():
    with pytest.raises(ValueError):
        ops.InvertedResidual(16, 16, 48, kernel_sizes=(3, 5), group_channels=(16,))
    with pytest.raises(ValueError):
        ops.InvertedResidual(16, 16, 48, kernel_sizes=(3, 5), group_channels=(40, 9))


def test_mask_zeroes_atoms_exact_equivalence():
    """Masked supernet forward == physically shrunk net forward (the central
    AtomNAS-on-XLA claim, SURVEY.md §7 hard part 1). Includes SE to prove the
    zero channels are invisible to the squeeze FCs."""
    full = ops.InvertedResidual(8, 8, 24, stride=1, kernel_sizes=(3, 5), group_channels=(12, 12), se_channels=6)
    params, state = full.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 6, 8))

    # kill channels 3..11 of branch0 and 0..5 of branch1 -> keep (3, 6)
    keep0 = np.arange(0, 3)
    keep1 = np.arange(12 + 6, 24)
    keep = np.concatenate([keep0, keep1])
    mask = np.zeros(24, np.float32)
    mask[keep] = 1.0

    y_masked, _ = full.apply(params, state, x, train=False, mask=jnp.asarray(mask))

    shrunk = ops.InvertedResidual(8, 8, 9, stride=1, kernel_sizes=(3, 5), group_channels=(3, 6), se_channels=6)
    sp = {
        "expand": {"w": params["expand"]["w"][..., keep]},
        "expand_bn": {k: v[keep] for k, v in params["expand_bn"].items()},
        "dw0_k3": {"w": params["dw0_k3"]["w"][..., keep0]},
        "dw1_k5": {"w": params["dw1_k5"]["w"][..., keep1 - 12]},
        "dw_bn": {k: v[keep] for k, v in params["dw_bn"].items()},
        "se": {
            "reduce": {"w": params["se"]["reduce"]["w"][keep, :], "b": params["se"]["reduce"]["b"]},
            "expand": {"w": params["se"]["expand"]["w"][:, keep], "b": params["se"]["expand"]["b"][keep]},
        },
        "project": {"w": params["project"]["w"][..., keep, :]},
        "project_bn": params["project_bn"],
    }
    ss = {
        "expand_bn": {k: v[keep] for k, v in state["expand_bn"].items()},
        "dw_bn": {k: v[keep] for k, v in state["dw_bn"].items()},
        "project_bn": state["project_bn"],
    }
    y_shrunk, _ = shrunk.apply(sp, ss, x, train=False)
    np.testing.assert_allclose(np.asarray(y_masked), np.asarray(y_shrunk), rtol=1e-5, atol=1e-5)
