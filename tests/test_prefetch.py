"""prefetch_to_mesh unit tests: ordering, finite drain, eager validation."""

import numpy as np
import pytest

import jax

from yet_another_mobilenet_series_tpu.parallel import mesh as mesh_lib


def _batches(n):
    for i in range(n):
        yield {"image": np.full((8, 2, 2, 3), i, np.float32), "label": np.full((8,), i, np.int32)}


def test_prefetch_preserves_order_and_drains():
    m = mesh_lib.make_mesh(8)
    it = mesh_lib.prefetch_to_mesh(_batches(5), m, depth=3)
    seen = [int(np.asarray(b["label"])[0]) for b in it]
    assert seen == [0, 1, 2, 3, 4]


def test_prefetch_shorter_than_depth():
    m = mesh_lib.make_mesh(8)
    it = mesh_lib.prefetch_to_mesh(_batches(2), m, depth=4)
    assert len(list(it)) == 2


def test_prefetch_batches_are_on_mesh():
    m = mesh_lib.make_mesh(8)
    b = next(mesh_lib.prefetch_to_mesh(_batches(1), m, depth=1))
    assert b["image"].sharding.spec == jax.sharding.PartitionSpec("data")


def test_prefetch_depth_validated_eagerly():
    m = mesh_lib.make_mesh(8)
    with pytest.raises(ValueError):
        mesh_lib.prefetch_to_mesh(_batches(3), m, depth=0)  # no next() needed
