"""Quantized serving tests (docs/SERVING.md "Quantized serving").

The load-bearing claims, each pinned:

- **uint8 wire parity**: the u8 wire serves the SAME answers as the f32
  wire fed :func:`serve.quant.normalize_reference` pixels — BITWISE when
  the denorm is shift-free (zero mean: a single per-channel multiply the
  backend cannot re-associate), and within ``serve.quant.wire_atol`` for
  the general mean/std case (the backend may FMA-fuse the prelude). The
  matrix crosses buckets x fused K in {1, 2, 4} x overlap on/off x the
  sharded path, so every existing serving structure is pinned under the
  quantized wire.
- **wire byte accounting**: a u8 dispatch puts EXACTLY 1/4 of the f32
  wire's bytes on the H2D wire (``serve.h2d_bytes``).
- **int8 weights**: export-time per-output-channel symmetric quantization
  is deterministic (same weights + batch + seed -> identical scales and
  ranges), top-1-agreement gated (a failing gate REFUSES to export), and
  the bundle round-trips through disk with scales + calibration provenance
  intact (``load_bundle`` -> identical logits bitwise).
- **composition**: int8 weights + uint8 wire + fused K + overlapped staging
  in one engine still match the chained reference bitwise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import ModelConfig, QuantConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.parallel import mesh as mesh_lib
from yet_another_mobilenet_series_tpu.serve import quant
from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
from yet_another_mobilenet_series_tpu.serve.export import (
    InferenceBundle,
    apply_folded,
    export_bundle,
    flatten_tree,
    fold_network,
    load_bundle,
)
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)
# the configured uint8-wire parity bar for the NON-bitwise (nonzero-mean)
# case: measured deltas are ~0..1e-5 on the test nets (the backend usually
# compiles the prelude identically; the gate is for the FMA-fusing case)
WIRE_ATOL = QuantConfig().wire_atol


def _small_net(num_classes=10, image_size=24, atom=False):
    specs = [
        {"t": 2, "c": 8, "n": 1, "s": 2, "k": [3, 5] if atom else 3, "se": 0.25 if atom else 0},
        {"t": 3, "c": 16, "n": 2, "s": 2},
    ]
    return get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=num_classes, block_specs=specs, dropout=0.0),
        image_size=image_size,
    )


def _folded_bundle(seed=0, atom=False):
    net = _small_net(atom=atom)
    params, state = net.init(jax.random.PRNGKey(seed))
    k = jax.random.PRNGKey(seed + 1)
    leaves, treedef = jax.tree.flatten(state)
    keys = jax.random.split(k, len(leaves))
    state = jax.tree.unflatten(
        treedef,
        [l + 0.1 * jnp.abs(jax.random.normal(kk, l.shape)) + 0.01 for l, kk in zip(leaves, keys)],
    )
    folded = fold_network(net, params, state)
    return net, folded, InferenceBundle(net=net, params=folded, meta={})


@pytest.fixture(scope="module")
def bundle():
    return _folded_bundle()


def _raw(n, size=24, seed=0):
    return np.random.RandomState(seed).randint(0, 256, (n, size, size, 3)).astype(np.uint8)


def _engines(bundle, *, mean=None, std=None, overlap=False, fuse=(2, 4), mesh=None):
    """(f32-wire, u8-wire) engine pair sharing one bundle and structure."""
    common = dict(buckets=(2, 4), image_size=24, fuse_ladder=fuse, mesh=mesh,
                  overlap_staging=overlap)
    return (
        InferenceEngine(bundle, **common),
        InferenceEngine(bundle, wire="uint8", wire_mean=mean, wire_std=std, **common),
    )


# ---------------------------------------------------------------------------
# rung 1: the uint8 wire
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("overlap", [False, True])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_wire_u8_bitwise_shift_free(bundle, k, overlap):
    """Zero-mean denorm is a single per-channel multiply: u8-wire logits are
    BITWISE identical to the f32 wire fed the host reference pixels, across
    fused K and both staging modes (the 'fold is exact' regime)."""
    _, _, b = bundle
    e_f32, e_u8 = _engines(b, overlap=overlap)
    assert e_u8.wire_parity_exact
    raw = _raw(k * 4, seed=k)
    handle = e_u8.predict_async(raw)
    assert handle.dispatches == (1 if k in (1, 2, 4) else None)
    got = handle.result()
    ref = e_f32.predict(quant.normalize_reference(raw))
    assert np.array_equal(got, ref)
    assert got.dtype == np.float32


@pytest.mark.parametrize("k", [1, 2])
def test_wire_u8_imagenet_norm_delta_gated(bundle, k):
    """Nonzero mean: the prelude carries an additive shift the backend may
    FMA-fuse, so parity is the measured-delta gate (recorded; usually 0)."""
    _, _, b = bundle
    e_f32, e_u8 = _engines(b, mean=IMAGENET_MEAN, std=IMAGENET_STD)
    assert not e_u8.wire_parity_exact
    raw = _raw(k * 4, seed=10 + k)
    got = e_u8.predict(raw)
    ref = e_f32.predict(quant.normalize_reference(raw, IMAGENET_MEAN, IMAGENET_STD))
    delta = float(np.max(np.abs(got - ref)))
    assert delta <= WIRE_ATOL, delta


def test_wire_u8_padded_small_buckets(bundle):
    """Off-bucket sizes pad with u8 zeros; real rows stay bitwise."""
    _, _, b = bundle
    e_f32, e_u8 = _engines(b)
    for n in (1, 3, 5):  # pads into bucket 2 / 4 / fused tail territory
        raw = _raw(n, seed=20 + n)
        assert np.array_equal(
            e_u8.predict(raw), e_f32.predict(quant.normalize_reference(raw)))


def test_wire_u8_float_inputs_round_not_truncate(bundle):
    """A float client array on the u8 wire is rounded-and-clipped to the
    pixel range (astype alone would truncate and wrap negatives)."""
    _, _, b = bundle
    _, e_u8 = _engines(b)
    raw = _raw(2, seed=30)
    as_float = raw.astype(np.float64) + 0.4  # rounds back down to raw
    assert np.array_equal(e_u8.predict(as_float), e_u8.predict(raw))
    clipped = np.full((2, 24, 24, 3), -7.0, np.float32)  # clips to 0
    assert np.array_equal(e_u8.predict(clipped), e_u8.predict(np.zeros((2, 24, 24, 3), np.uint8)))


def test_wire_u8_h2d_bytes_quarter(bundle):
    """The precise wire instrument: a u8 dispatch puts exactly 1/4 of the
    f32 wire's bytes on H2D (serve.h2d_bytes registry deltas)."""
    _, _, b = bundle
    e_f32, e_u8 = _engines(b)
    raw = _raw(4, seed=40)
    reg = get_registry()
    e_u8.predict(raw)  # warm both so the measured window is steady-state
    e_f32.predict(quant.normalize_reference(raw))
    s0 = reg.snapshot().get("serve.h2d_bytes", 0)
    e_u8.predict(raw)
    s1 = reg.snapshot().get("serve.h2d_bytes", 0)
    e_f32.predict(quant.normalize_reference(raw))
    s2 = reg.snapshot().get("serve.h2d_bytes", 0)
    u8_bytes, f32_bytes = s1 - s0, s2 - s1
    assert u8_bytes == 4 * 24 * 24 * 3
    assert f32_bytes == 4 * u8_bytes


def test_wire_u8_sharded_path(bundle):
    """The mesh path stages u8, snapshots u8, and denormalizes on device:
    sharded u8 == sharded f32-wire reference bitwise (and the sharded
    result equals the unsharded one, the existing dp-engine invariant)."""
    _, _, b = bundle
    mesh = mesh_lib.make_mesh()
    if mesh.size < 2:
        pytest.skip("needs >= 2 devices (conftest fakes 8 CPU devices)")
    buckets = (mesh.size,)  # sharded buckets must divide the mesh
    e_f32 = InferenceEngine(b, buckets=buckets, image_size=24, fuse_ladder=(), mesh=mesh)
    e_u8 = InferenceEngine(b, buckets=buckets, image_size=24, fuse_ladder=(), mesh=mesh,
                           wire="uint8")
    raw = _raw(mesh.size // 2, seed=50)  # padded: the staging pool engages
    ref = e_f32.predict(quant.normalize_reference(raw))
    assert np.array_equal(e_u8.predict(raw), ref)
    # vs the UNSHARDED u8 engine: a different XLA partitioning, so f32
    # rounding only (the same bar the existing dp-engine test uses)
    e_plain = InferenceEngine(b, buckets=buckets, image_size=24, fuse_ladder=(), wire="uint8")
    np.testing.assert_allclose(e_plain.predict(raw), ref, atol=1e-5, rtol=0)


def test_wire_u8_overlap_slot_reuse(bundle):
    """u8 staging slots recycle under overlap exactly like f32 ones: a
    stream of distinct batches through a 2-slot pool stays bitwise per
    batch (torn-write protection is dtype-independent)."""
    _, _, b = bundle
    e_f32, e_u8 = _engines(b, overlap=True)
    batches = [_raw(3, seed=60 + i) for i in range(6)]  # padded: slots engaged
    handles = [e_u8.predict_async(r) for r in batches]
    for raw, h in zip(batches, handles):
        assert np.array_equal(h.result(), e_f32.predict(quant.normalize_reference(raw)))


def test_wire_u8_through_pipelined_batcher(bundle):
    """End to end through the real batcher: the wire dtype rides the engine
    (PipelinedBatcher inherits it), submit coerces once, and every client
    row comes back bitwise-correct."""
    _, _, b = bundle
    e_f32, e_u8 = _engines(b)
    batcher = PipelinedBatcher(e_u8, max_batch=4, max_wait_ms=5.0).start()
    try:
        assert batcher._wire_dtype == np.uint8
        raw = _raw(6, seed=70)
        futs = [batcher.submit(raw[i]) for i in range(6)]
        rows = np.stack([f.result(timeout=30) for f in futs])
    finally:
        batcher.stop()
    ref = e_f32.predict(quant.normalize_reference(raw))
    assert np.array_equal(rows, ref)


# ---------------------------------------------------------------------------
# rung 2: int8 weights
# ---------------------------------------------------------------------------


def _calib(n=16, seed=3):
    return quant.normalize_reference(_raw(n, seed=seed), IMAGENET_MEAN, IMAGENET_STD)


def test_int8_quantize_deterministic(bundle):
    """Same weights + same batch + same everything -> identical scales,
    identical quantized ints, identical activation ranges (the calibration
    determinism contract)."""
    net, folded, _ = bundle
    calib = _calib()
    q1, r1 = quant.calibrate_and_quantize(net, folded, calib, top1_min=0.5)
    q2, r2 = quant.calibrate_and_quantize(net, folded, calib, top1_min=0.5)
    f1, f2 = flatten_tree(q1), flatten_tree(q2)
    assert f1.keys() == f2.keys()
    for k in f1:
        assert np.array_equal(f1[k], f2[k]), k
    assert r1["calib"]["activation_ranges"] == r2["calib"]["activation_ranges"]
    assert r1["top1_agreement"] == r2["top1_agreement"]


def test_int8_scales_per_output_channel(bundle):
    """Per-output-channel symmetric: every quantized pair carries a scale
    per OUTPUT channel (the last weight axis), int8 storage, f32 bias."""
    net, folded, _ = bundle
    q, n = quant.quantize_folded(folded)
    assert n >= 8  # stem + expands + dws + projects + classifier at least
    flat = flatten_tree(q)
    qkeys = [k for k in flat if k.endswith("/w_q")]
    assert qkeys
    for k in qkeys:
        base = k[: -len("/w_q")]
        w_q, scale = flat[k], flat[base + "/w_scale"]
        assert w_q.dtype == np.int8 and scale.dtype == np.float32
        assert scale.shape == (w_q.shape[-1],)
        assert np.abs(w_q).max() <= 127
        # dequantization reconstructs within half a quantization step
        orig = flatten_tree(folded)[base + "/w"]
        step = scale.reshape((1,) * (orig.ndim - 1) + (-1,))
        assert np.max(np.abs(quant.dequantize_array(w_q, scale) - orig) / step) <= 0.5 + 1e-6


def test_int8_gate_refuses_bad_agreement(bundle):
    """An unmeetable gate refuses the export loudly (QuantParityError) —
    never a silently-wrong artifact."""
    net, folded, _ = bundle
    with pytest.raises(quant.QuantParityError, match="top-1 agreement"):
        quant.calibrate_and_quantize(net, folded, _calib(), top1_min=1.0 + 1e-9)


def test_int8_export_roundtrip(tmp_path, bundle):
    """export_bundle(quant_weights='int8') -> load_bundle round-trips the
    int8 ints, the f32 scales, and the calibration provenance; the loaded
    bundle serves bitwise-identically to the in-memory quantized tree."""
    net = _small_net(atom=True)
    params, state = net.init(jax.random.PRNGKey(7))
    calib = _calib()
    out = export_bundle(
        net, params, state, str(tmp_path / "b"),
        quant_weights="int8", calib_images=calib, int8_top1_min=0.5,
    )
    loaded = load_bundle(out)
    assert loaded.quant is not None
    assert loaded.quant["weights"] == "int8"
    assert loaded.quant["scheme"] == "per_output_channel_symmetric"
    assert 0.5 <= loaded.quant["top1_agreement"] <= 1.0
    assert loaded.quant["top1_min"] == 0.5
    assert loaded.quant["bytes_int8"] < 0.5 * loaded.quant["bytes_f32"]
    assert loaded.quant["calib"]["images"] == calib.shape[0]
    assert loaded.quant["calib"]["activation_ranges"]  # ranges serialized
    flat = flatten_tree(loaded.params)
    assert any(k.endswith("/w_q") for k in flat)
    assert all(flat[k].dtype == np.int8 for k in flat if k.endswith("/w_q"))
    # the loaded tree serves identically to a freshly quantized one
    folded = fold_network(net, params, state)
    q, _ = quant.quantize_folded(folded)
    x = _calib(4, seed=9)
    assert np.array_equal(
        np.asarray(apply_folded(net, loaded.params, x)),
        np.asarray(apply_folded(net, q, x)),
    )


def test_int8_top1_agreement_on_heldout(bundle):
    """The exported int8 forward agrees with f32 top-1 on a batch the
    calibration never saw (the gate generalizes past its own batch)."""
    net, folded, _ = bundle
    q, report = quant.calibrate_and_quantize(net, folded, _calib(), top1_min=0.5)
    x = _calib(24, seed=99)
    ref = np.asarray(apply_folded(net, folded, x))
    got = np.asarray(apply_folded(net, q, x))
    assert float(np.mean(np.argmax(got, -1) == np.argmax(ref, -1))) >= report["top1_min"]


@pytest.mark.slow
def test_int8_gate_across_seeds(bundle):
    """Calibration-heavy: the default gate holds across weight seeds (the
    quantization error of per-channel symmetric int8 stays far inside the
    top-1 bar on these nets)."""
    for seed in range(3):
        net, folded, _ = _folded_bundle(seed=seed)
        _, report = quant.calibrate_and_quantize(
            net, folded, _calib(32, seed=seed), top1_min=QuantConfig().int8_top1_min)
        assert report["top1_agreement"] >= QuantConfig().int8_top1_min


# ---------------------------------------------------------------------------
# composition: both rungs + every serving structure
# ---------------------------------------------------------------------------


def test_int8_u8_wire_fused_overlap_compose(bundle):
    """The cheap-request end state: int8 weights + uint8 wire + fused K +
    overlapped staging in ONE engine. Structure invariance (fused/overlap
    vs chained, same quantized params) is bitwise; accuracy vs the f32
    bundle is the top-1 gate."""
    net, folded, b_f32 = bundle
    q, report = quant.calibrate_and_quantize(net, folded, _calib(), top1_min=0.5)
    b_q = InferenceBundle(net=net, params=q, meta={"quant": report})
    common = dict(buckets=(2, 4), image_size=24, wire="uint8",
                  wire_mean=IMAGENET_MEAN, wire_std=IMAGENET_STD)
    e_chained = InferenceEngine(b_q, fuse_ladder=(), **common)
    e_full = InferenceEngine(b_q, fuse_ladder=(2, 4), overlap_staging=True,
                             staging_slots=2, **common)
    assert e_full.quant_mode == "wire=uint8,weights=int8"
    raw = _raw(8, seed=80)  # 2 fused chunks of bucket 4
    ref_q = e_chained.predict(raw)
    h = e_full.predict_async(raw)
    assert h.dispatches == 1  # the fused scan covered the whole request
    assert np.array_equal(h.result(), ref_q)
    # and the composed engine still agrees with the full-precision bundle
    e_ref = InferenceEngine(b_f32, buckets=(2, 4), image_size=24, fuse_ladder=())
    ref = e_ref.predict(quant.normalize_reference(raw, IMAGENET_MEAN, IMAGENET_STD))
    agree = float(np.mean(np.argmax(ref_q, -1) == np.argmax(ref, -1)))
    assert agree >= report["top1_min"]


def test_cost_keys_do_not_collide_across_modes(bundle):
    """Two engines with different quant modes in one process must not
    cross-write each other's per-executable cost gauges (the A/B bench runs
    exactly this shape): the keys carry wire/weight tags."""
    from yet_another_mobilenet_series_tpu.obs import device as obs_device

    _, _, b = bundle
    e_f32, e_u8 = _engines(b, fuse=())
    e_f32.predict(quant.normalize_reference(_raw(2, seed=90)))
    e_u8.predict(_raw(2, seed=90))
    report = obs_device.compile_report()
    assert "serve_b2_s24_k1" in report
    assert "serve_b2_s24_k1_u8" in report
    # the u8 program's cost bytes must not be (silently) the f32 one's
    assert report["serve_b2_s24_k1"] != report["serve_b2_s24_k1_u8"]


# ---------------------------------------------------------------------------
# quant.py unit edges
# ---------------------------------------------------------------------------


def test_denorm_constants_identity_and_validation():
    scale, shift = quant.denorm_constants(None, None)
    assert np.allclose(scale, np.float32(1.0 / 255.0)) and quant.shift_free(shift)
    scale, shift = quant.denorm_constants(IMAGENET_MEAN, IMAGENET_STD)
    assert not quant.shift_free(shift)
    with pytest.raises(ValueError, match="positive"):
        quant.denorm_constants(None, (0.0, 1.0, 1.0))
    with pytest.raises(ValueError, match="3-channel"):
        quant.denorm_constants((0.5,), None)
    with pytest.raises(ValueError, match="wire"):
        quant.wire_np_dtype("int4")


def test_quantize_zero_channel_never_divides_by_zero():
    w = np.zeros((3, 3, 4, 8), np.float32)
    w[..., :4] = np.random.RandomState(0).normal(0, 1, (3, 3, 4, 4))
    w_q, scale = quant.quantize_array_int8(w)
    assert np.all(scale[4:] == 1.0)  # dead channels get the safe scale
    assert np.all(w_q[..., 4:] == 0)
    assert np.isfinite(quant.dequantize_array(w_q, scale)).all()
