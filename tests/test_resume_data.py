"""Resume must CONTINUE the train data order, not restart it (VERDICT r3 #2;
SURVEY.md §5 checkpoint bullet): a run interrupted at step k and resumed
with make_train_source(..., start_step=k) — exactly what cli/train.py passes
(int(ts.step)) — produces the same next-batch sequence as the uninterrupted
run.

- fake/tfdata and folder/native: BIT-EXACT equality (both derive every batch
  purely from (seed, stream position)).
- imagenet/TFRecord: exact under deterministic settings (decode_threads=1,
  shuffle_buffer=1) — this pins the epoch-keyed stateless file shuffle and
  the intra-epoch record skip; with parallel interleave the record order is
  approximate by design (pipeline.make_train_dataset docstring), but the
  epoch arithmetic under test here is the same.
"""

import itertools
import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")  # fixture JPEGs; repo convention (test_native_loader.py)
from PIL import Image  # noqa: E402

from yet_another_mobilenet_series_tpu.config import DataConfig  # noqa: E402
from yet_another_mobilenet_series_tpu.data import make_train_source  # noqa: E402


def _take(it, n):
    return list(itertools.islice(it, n))


def _assert_batches_equal(resumed, reference, path_name):
    assert len(resumed) == len(reference)
    for i, (a, b) in enumerate(zip(resumed, reference)):
        np.testing.assert_array_equal(a["label"], b["label"], err_msg=f"{path_name} batch {i}")
        np.testing.assert_array_equal(a["image"], b["image"], err_msg=f"{path_name} batch {i}")


def test_fake_tfdata_resume_continues_stream():
    cfg = DataConfig(dataset="fake", loader="tfdata", image_size=8,
                     fake_train_size=32, fake_num_classes=4)
    full = _take(make_train_source(cfg, local_batch=4, seed=7), 12)
    # interrupt at step 5: the resumed source must yield batches 5..11
    resumed = _take(make_train_source(cfg, local_batch=4, seed=7, start_step=5), 7)
    _assert_batches_equal(resumed, full[5:], "fake/tfdata")
    # crossing an epoch boundary (32 samples / batch 4 = 8 batches/epoch)
    resumed = _take(make_train_source(cfg, local_batch=4, seed=7, start_step=9), 3)
    _assert_batches_equal(resumed, full[9:], "fake/tfdata epoch-crossing")


def _jpeg_tree(root, n_classes=2, per_class=6, size=16):
    rs = np.random.RandomState(0)
    for c in range(n_classes):
        d = os.path.join(root, "train", f"c{c}")
        os.makedirs(d)
        for i in range(per_class):
            img = Image.fromarray(rs.randint(0, 255, (size, size, 3), np.uint8))
            img.save(os.path.join(d, f"{i}.jpg"), quality=95, subsampling=0)


def test_native_resume_continues_stream(tmp_path):
    _jpeg_tree(str(tmp_path))
    cfg = DataConfig(dataset="folder", loader="native", data_dir=str(tmp_path),
                     image_size=8, decode_threads=2)
    full = _take(make_train_source(cfg, local_batch=4, seed=3), 9)
    # 12 samples / batch 4 = 3 batches/epoch; step 4 is inside epoch 1
    resumed = _take(make_train_source(cfg, local_batch=4, seed=3, start_step=4), 5)
    _assert_batches_equal(resumed, full[4:], "folder/native")


def _write_tfrecords(dst, n_shards=3, per_shard=7, img_size=16, shard_sizes=None):
    import tensorflow as tf

    os.makedirs(dst)
    rs = np.random.RandomState(1)
    shard_sizes = shard_sizes or [per_shard] * n_shards
    for s, n_recs in enumerate(shard_sizes):
        path = os.path.join(dst, f"train-{s:05d}-of-{len(shard_sizes):05d}")
        with tf.io.TFRecordWriter(path) as w:
            for i in range(n_recs):
                img = Image.fromarray(rs.randint(0, 255, (img_size, img_size, 3), np.uint8))
                import io

                buf = io.BytesIO()
                img.save(buf, format="JPEG", quality=95, subsampling=0)
                # distinctive label encodes (shard, record) so the label
                # sequence uniquely identifies the record order
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[buf.getvalue()])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[s * 100 + i + 1])),
                }))
                w.write(ex.SerializeToString())


def test_tfrecord_resume_continues_epoch_order(tmp_path):
    """Deterministic settings (1 interleave stream, no-op shuffle buffer)
    make the TFRecord label sequence a pure function of (seed, position):
    resuming mid-epoch and across an epoch boundary must reproduce the
    uninterrupted run's label stream — pinning the stateless (seed, epoch)
    file permutation and the intra-epoch record skip. 21 records with
    batch 4 put epoch boundaries MID-batch: batching runs over the
    continuous record stream, so the resume arithmetic must count records,
    not whole batches per epoch (a batch-floor would drift 1 record/epoch
    here)."""
    _write_tfrecords(str(tmp_path / "rec"))  # 3 shards x 7 records
    cfg = DataConfig(dataset="imagenet", loader="tfdata", data_dir=str(tmp_path / "rec"),
                     image_size=8, num_train_examples=21,
                     decode_threads=1, shuffle_buffer=1)
    # 12 batches = 48 records = 2.28 epochs
    full = [b["label"] for b in _take(make_train_source(cfg, local_batch=4, seed=11), 12)]
    for start in (2, 5, 8):  # mid-epoch-0, epoch-0 tail, inside epoch 1
        resumed = [b["label"] for b in
                   _take(make_train_source(cfg, local_batch=4, seed=11, start_step=start), 12 - start)]
        for i, (a, b) in enumerate(zip(resumed, full[start:])):
            np.testing.assert_array_equal(a, b, err_msg=f"start={start} batch {i}")
    # and epoch 1's file order actually differs from epoch 0's (the shuffle
    # is real, not an identity permutation): shard id = label // 100
    stream = np.concatenate(full)
    e0, e1 = stream[:21] // 100, stream[21:42] // 100
    assert not np.array_equal(e0, e1)

    # data.deterministic_input gives the same guarantee WITHOUT hand-pinning
    # decode_threads/shuffle_buffer (the production-facing switch: single
    # deterministic interleave stream, file permutation as the only
    # shuffle) — and because the augmentations are stateless (keyed by
    # stream position), the guarantee covers PIXELS: resume and independent
    # rebuilds are bit-identical end-to-end, not just record-exact
    det_cfg = DataConfig(dataset="imagenet", loader="tfdata", data_dir=str(tmp_path / "rec"),
                         image_size=8, num_train_examples=21,
                         decode_threads=4, shuffle_buffer=16384, deterministic_input=True)
    det_full = _take(make_train_source(det_cfg, local_batch=4, seed=11), 12)
    det_resumed = _take(make_train_source(det_cfg, local_batch=4, seed=11, start_step=5), 7)
    _assert_batches_equal(det_resumed, det_full[5:], "deterministic_input resume")
    det_again = _take(make_train_source(det_cfg, local_batch=4, seed=11), 12)
    _assert_batches_equal(det_again, det_full, "deterministic_input rebuild")

    # uneven multi-host shards (host 0 reads 2 of 3 files = 14 records/epoch,
    # host 1 reads 7): the epoch arithmetic must use THIS host's file
    # fraction, or a resumed host drifts whole epochs from the uninterrupted
    # stream
    for pi, pc in ((0, 2), (1, 2)):
        host_full = [b["label"] for b in _take(
            make_train_source(cfg, local_batch=4, seed=11,
                              process_index=pi, process_count=pc), 10)]
        for start in (3, 7):
            resumed = [b["label"] for b in _take(
                make_train_source(cfg, local_batch=4, seed=11, process_index=pi,
                                  process_count=pc, start_step=start), 10 - start)]
            for i, (a, b) in enumerate(zip(resumed, host_full[start:])):
                np.testing.assert_array_equal(a, b, err_msg=f"host {pi}/{pc} start={start} batch {i}")


def test_tfrecord_resume_uneven_shards_exact(tmp_path):
    """UNEVEN shards (7/3/11 records) break the equal-shards estimate the
    resume arithmetic used before ADVICE r4 #1: host 0 of 2 reads shards
    {0,2} = 18 records/epoch where the estimate says ceil(21*2/3) = 14 — a
    4-record/epoch drift that compounds every epoch crossed. The arithmetic
    now counts records per shard (TFRecord framing walk), so resume must be
    label-exact under deterministic settings regardless of shard balance."""
    from yet_another_mobilenet_series_tpu.data import pipeline as pl

    _write_tfrecords(str(tmp_path / "rec"), shard_sizes=[7, 3, 11])
    # the framing walk itself, against known counts
    files = sorted(os.listdir(tmp_path / "rec"))
    counts = [pl._count_tfrecord_records(str(tmp_path / "rec" / f))
              for f in files if not f.startswith(".")]
    assert counts == [7, 3, 11]

    cfg = DataConfig(dataset="imagenet", loader="tfdata", data_dir=str(tmp_path / "rec"),
                     image_size=8, num_train_examples=21,
                     decode_threads=1, shuffle_buffer=1)
    # single host: 12 batches x 4 = 48 records = 2.28 epochs of 21
    full = [b["label"] for b in _take(make_train_source(cfg, local_batch=4, seed=5), 12)]
    for start in (2, 6, 9):
        resumed = [b["label"] for b in
                   _take(make_train_source(cfg, local_batch=4, seed=5, start_step=start), 12 - start)]
        for i, (a, b) in enumerate(zip(resumed, full[start:])):
            np.testing.assert_array_equal(a, b, err_msg=f"uneven start={start} batch {i}")
    # two hosts with maximally uneven shares: host 0 -> 18 rec/epoch,
    # host 1 -> 3 rec/epoch (deep into epoch space after a few batches)
    for pi, pc in ((0, 2), (1, 2)):
        host_full = [b["label"] for b in _take(
            make_train_source(cfg, local_batch=4, seed=5,
                              process_index=pi, process_count=pc), 10)]
        for start in (3, 7):
            resumed = [b["label"] for b in _take(
                make_train_source(cfg, local_batch=4, seed=5, process_index=pi,
                                  process_count=pc, start_step=start), 10 - start)]
            for i, (a, b) in enumerate(zip(resumed, host_full[start:])):
                np.testing.assert_array_equal(
                    a, b, err_msg=f"uneven host {pi}/{pc} start={start} batch {i}")
    # the sidecar cache was written and holds the exact counts
    import json

    with open(tmp_path / "rec" / ".record_counts.json") as f:
        disk = json.load(f)
    assert sorted(int(v) for v in disk.values()) == [3, 7, 11]


@pytest.mark.slow
def test_tfrecord_resume_fuzz_random_shards_and_hosts(tmp_path):
    """Property-style sweep of the exact-resume arithmetic: random uneven
    shard sizes, host splits, batch sizes, and resume points must all give
    label-exact continuation under deterministic_input. Complements the
    hand-picked cases above — the arithmetic has three interacting moduli
    (records/epoch per host, records/batch, epoch file permutation) and
    off-by-ones live at their intersections."""
    rs = np.random.RandomState(42)
    case_dirs = {}
    for case in range(6):
        shard_sizes = [int(rs.randint(1, 10)) for _ in range(int(rs.randint(2, 5)))]
        total = sum(shard_sizes)
        key = tuple(shard_sizes)
        if key not in case_dirs:
            d = tmp_path / f"rec{case}"
            _write_tfrecords(str(d), shard_sizes=shard_sizes, img_size=8)
            case_dirs[key] = str(d)
        local_batch = int(rs.randint(2, 5))
        pc = int(rs.randint(1, 3))
        cfg = DataConfig(dataset="imagenet", loader="tfdata", data_dir=case_dirs[key],
                         image_size=8, num_train_examples=total,
                         deterministic_input=True)
        for pi in range(pc):
            if not list(range(len(shard_sizes)))[pi::pc]:
                continue  # a zero-shard host raises by design; skip
            seed = int(rs.randint(0, 1000))
            n_batches = 8
            full = [b["label"] for b in _take(
                make_train_source(cfg, local_batch, seed=seed,
                                  process_index=pi, process_count=pc), n_batches)]
            start = int(rs.randint(1, n_batches))
            resumed = [b["label"] for b in _take(
                make_train_source(cfg, local_batch, seed=seed, process_index=pi,
                                  process_count=pc, start_step=start), n_batches - start)]
            for i, (a, b) in enumerate(zip(resumed, full[start:])):
                np.testing.assert_array_equal(
                    a, b, err_msg=f"case={case} shards={shard_sizes} host {pi}/{pc} "
                                  f"batch={local_batch} start={start} batch#{i}")


@pytest.mark.slow
def test_cli_passes_restored_step_as_start_step(tmp_path, monkeypatch):
    """Behavioral pin of the CLI wiring the stream tests above rely on: a
    fresh run builds its train source at start_step=0 and a resumed run at
    the restored step — observed by wrapping the real make_train_source the
    CLI calls (a source-string assert would break on any refactor and catch
    nothing real)."""
    import yet_another_mobilenet_series_tpu.data as data_mod
    from yet_another_mobilenet_series_tpu.cli import train as cli_train
    from yet_another_mobilenet_series_tpu.config import config_from_dict

    recorded = []
    real = data_mod.make_train_source

    def recording(cfg, local_batch, seed, process_index=0, process_count=1, start_step=0):
        recorded.append(start_step)
        return real(cfg, local_batch, seed, process_index, process_count, start_step=start_step)

    monkeypatch.setattr(data_mod, "make_train_source", recording)

    def cfg_for(epochs):
        return config_from_dict({
            "name": "resume_wiring",
            "model": {"arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
                      "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}]},
            "data": {"dataset": "fake", "image_size": 16, "fake_train_size": 128,
                     "fake_eval_size": 32, "fake_num_classes": 4},
            "optim": {"optimizer": "sgd", "weight_decay": 0.0},
            "schedule": {"schedule": "constant", "base_lr": 0.05,
                         "scale_by_batch": False, "warmup_epochs": 0.0},
            "ema": {"enable": False},
            "train": {"batch_size": 32, "eval_batch_size": 32, "epochs": epochs,
                      "compute_dtype": "float32", "log_dir": str(tmp_path),
                      "eval_every_epochs": 0.0},
            "dist": {"num_devices": 8},
        })

    cli_train.run(cfg_for(1))   # fresh: 128/32 = 4 steps
    cli_train.run(cfg_for(2))   # resumed at step 4
    assert recorded == [0, 4], recorded
