"""Brownout: the graceful-degradation ladder (serve/brownout.py) and the
shared windowed-signal reader (serve/signals.py) both control loops consume.

The ladder's DECISIONS are tested on scripted signal traces with injected
clocks (every transition, asymmetric hysteresis, cooldown pacing,
flap-resistance, full recovery); its ACTUATION is tested per layer
(admission class shed with Retry-After, batcher fill-or-flush, retry
disable, deadline-margin tightening); and one e2e storm smoke drives a real
HTTP frontend 3x past a fake engine's capacity and asserts the headline
claim: interactive availability holds while best_effort sheds at the door,
and the ladder fully recovers to L0 after the storm."""

import threading
import time

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.obs.registry import get_registry, quantiles_from_counts
from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController, BrownoutShed
from yet_another_mobilenet_series_tpu.serve.brownout import (
    MAX_LEVEL,
    BrownoutController,
    BrownoutPolicy,
    build_ladder,
)
from yet_another_mobilenet_series_tpu.serve.client import ClientHTTPError, ReplicaClient
from yet_another_mobilenet_series_tpu.serve.faults import InjectedFault
from yet_another_mobilenet_series_tpu.serve.frontend import Frontend
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher
from yet_another_mobilenet_series_tpu.serve.signals import SignalReader, Signals


def _snap(key):
    return get_registry().snapshot().get(key, 0)


def _sig(p99_ms=None, queue=0.0, breaker=0):
    return Signals(
        p99_s=None if p99_ms is None else p99_ms / 1e3,
        queue_depth=queue,
        breaker_state=breaker,
    )


class _FakeTarget:
    """Records every policy push (the actuation contract)."""

    def __init__(self):
        self.applied: list[BrownoutPolicy] = []

    def apply_brownout(self, policy):
        self.applied.append(policy)


def _controller(**kw):
    get_registry().reset()
    target = _FakeTarget()
    kw.setdefault("up_p99_ms", 100.0)
    kw.setdefault("down_p99_ms", 20.0)
    kw.setdefault("up_queue_depth", 8.0)
    kw.setdefault("down_queue_depth", 1.0)
    kw.setdefault("hold_up_s", 1.0)
    kw.setdefault("cooldown_s", 5.0)
    reader = SignalReader(latency_family="serve.latency_seconds",
                          signal_class="interactive")
    return BrownoutController(reader, (target,), **kw), target


# ---------------------------------------------------------------------------
# the ladder itself (build_ladder ordering invariants)
# ---------------------------------------------------------------------------


def test_ladder_is_ordered_and_monotone():
    """Each level keeps every degradation below it: hedging dies first
    (L1), linger second (L2), classes shed outward from best_effort (L3)
    through batch (L4), and only survival mode (L5) spends no retries."""
    ladder = build_ladder()
    assert len(ladder) == MAX_LEVEL + 1
    assert [p.level for p in ladder] == list(range(MAX_LEVEL + 1))
    assert [p.hedging for p in ladder] == [True] + [False] * 5
    assert [p.fill_or_flush for p in ladder] == [False, False] + [True] * 4
    assert [sorted(p.shed_classes) for p in ladder] == [
        [], [], [], ["best_effort"], ["batch", "best_effort"], ["batch", "best_effort"]]
    assert [p.retries for p in ladder] == [True] * 5 + [False]
    # the deadline margin only tightens, never relaxes, up the ladder
    margins = [p.deadline_margin for p in ladder]
    assert margins == sorted(margins) and margins[0] == 1.0 and margins[-1] > margins[3]
    # interactive is NEVER shed: survival mode exists to protect it
    assert all("interactive" not in p.shed_classes for p in ladder)


# ---------------------------------------------------------------------------
# ladder decisions on scripted signal traces (injected clock, no threads)
# ---------------------------------------------------------------------------


def test_steps_up_one_level_per_hold_window():
    c, target = _controller(hold_up_s=1.0)
    assert c.level == 0 and len(target.applied) == 1  # L0 pushed at build
    row = c.step(now=10.0, signals=_sig(p99_ms=500))
    assert row["action"] == "up" and c.level == 1
    # still overloaded but inside the hold window: no double-step
    row = c.step(now=10.5, signals=_sig(p99_ms=500))
    assert row["action"] == "hold" and c.level == 1
    row = c.step(now=11.1, signals=_sig(p99_ms=500))
    assert row["action"] == "up" and c.level == 2
    assert [p.level for p in target.applied] == [0, 1, 2]
    assert _snap("serve.brownout_level") == 2
    assert _snap("serve.brownout_transitions") == 2
    assert _snap("serve.brownout_transitions.up") == 2


def test_climbs_to_max_level_and_stops():
    c, target = _controller(hold_up_s=0.5, max_level=5)
    t = 0.0
    for _ in range(12):
        t += 1.0
        c.step(now=t, signals=_sig(queue=100))  # queue alone is overload
    assert c.level == 5
    assert max(p.level for p in target.applied) == 5
    # at the top the ladder holds, it does not wrap or oscillate
    assert c.step(now=t + 1, signals=_sig(queue=100))["action"] == "hold"


def test_breaker_open_counts_as_overload():
    """Rejected requests never reach the latency histogram, so the breaker
    gauge must be overload evidence on its own."""
    c, _ = _controller()
    row = c.step(now=1.0, signals=_sig(p99_ms=None, queue=0.0, breaker=1))
    assert row["action"] == "up" and c.level == 1


def test_recovery_one_level_per_cooldown_and_full_return_to_l0():
    c, target = _controller(hold_up_s=0.1, cooldown_s=5.0)
    t = 0.0
    for _ in range(3):  # climb to L3
        t += 1.0
        c.step(now=t, signals=_sig(p99_ms=500))
    assert c.level == 3
    # relaxed signals: the FIRST down waits out the cooldown from the last
    # transition, then exactly one level per cooldown
    assert c.step(now=t + 1.0, signals=_sig(p99_ms=5, queue=0))["action"] == "hold"
    assert c.step(now=t + 5.1, signals=_sig(p99_ms=5, queue=0))["action"] == "down"
    assert c.level == 2
    assert c.step(now=t + 7.0, signals=_sig(p99_ms=5, queue=0))["action"] == "hold"
    assert c.step(now=t + 10.3, signals=_sig(p99_ms=5, queue=0))["action"] == "down"
    assert c.step(now=t + 15.5, signals=_sig(p99_ms=5, queue=0))["action"] == "down"
    assert c.level == 0
    # an IDLE window (no completions at all) is relaxed too: an idle server
    # must drain its ladder, not stick at L1 forever
    assert all(p99 is None or True for p99 in [None])
    assert _snap("serve.brownout_transitions.down") == 3
    assert _snap("serve.brownout_level") == 0
    assert [p.level for p in target.applied] == [0, 1, 2, 3, 2, 1, 0]


def test_dead_band_resists_flapping():
    """Signals oscillating INSIDE the dead band (between down and up
    thresholds) move the ladder in neither direction — the hysteresis
    contract that makes brownout a ratchet, not an oscillator."""
    c, _ = _controller(up_p99_ms=100.0, down_p99_ms=20.0, hold_up_s=0.1, cooldown_s=0.1)
    c.step(now=1.0, signals=_sig(p99_ms=500))
    assert c.level == 1
    for i in range(20):  # in-band p99 wobbling 30..90ms: neither up nor down
        row = c.step(now=2.0 + i, signals=_sig(p99_ms=30 + (i % 2) * 60))
        assert row["action"] == "hold", row
    assert c.level == 1


def test_idle_window_is_relaxed_and_recovers():
    c, _ = _controller(hold_up_s=0.1, cooldown_s=1.0)
    c.step(now=1.0, signals=_sig(queue=50))
    assert c.level == 1
    # p99 None (no completions) + empty queue = relaxed
    assert c.step(now=2.5, signals=_sig(p99_ms=None, queue=0))["action"] == "down"
    assert c.level == 0


def test_controller_validates_thresholds():
    with pytest.raises(ValueError, match="dead band|thresholds"):
        _controller(up_p99_ms=50.0, down_p99_ms=50.0)
    with pytest.raises(ValueError, match="max_level"):
        _controller(max_level=9)


# ---------------------------------------------------------------------------
# the shared signal reader (serve/signals.py)
# ---------------------------------------------------------------------------


def test_windowed_quantile_is_delta_math_not_whole_run():
    """The window p99 must reflect ONLY observations since the last read —
    pinned against quantiles_from_counts over the explicit bucket delta."""
    get_registry().reset()
    hist = get_registry().histogram("serve.latency_seconds.interactive")
    reader = SignalReader(latency_family="serve.latency_seconds",
                          signal_class="interactive")
    for _ in range(100):
        hist.observe(0.005)  # a calm past
    before = hist.bucket_counts()
    assert reader.read().p99_s == pytest.approx(
        quantiles_from_counts(hist.bounds, before, (0.99,))[0])
    # the storm arrives: the next window must see ONLY the storm
    for _ in range(50):
        hist.observe(1.0)
    after = hist.bucket_counts()
    delta = [a - b for a, b in zip(after, before)]
    expect = quantiles_from_counts(hist.bounds, delta, (0.99,))[0]
    got = reader.read().p99_s
    assert got == pytest.approx(expect)
    assert got > 0.5  # the calm past did NOT anchor the estimate
    # window consumed: an idle tick reads None
    assert reader.read().p99_s is None


def test_signal_reader_breaker_and_queue_depth():
    get_registry().reset()
    get_registry().gauge("serve.breaker_state").set(1)
    reader = SignalReader(latency_family="serve.latency_seconds",
                          signal_class="interactive", queue_depth_fn=lambda: 7.5)
    sig = reader.read()
    assert sig.breaker_open and sig.breaker_state == 1
    assert sig.queue_depth == 7.5
    get_registry().gauge("serve.breaker_state").set(0)
    assert not reader.read().breaker_open


def test_autoscaler_signal_parity_after_refactor():
    """The autoscaler consumes serve/signals.py now; its window math must
    be EXACTLY what it computed before the factor-out (pinned here against
    the registry's own quantile function over explicit deltas)."""
    from yet_another_mobilenet_series_tpu.serve.autoscale import Autoscaler

    get_registry().reset()

    class _F:
        n_replicas = 1

        def scale_to(self, n):
            return n

    class _R:
        def mean_queue_depth(self):
            return 0.0

    a = Autoscaler(_F(), _R(), min_replicas=1, max_replicas=2,
                   up_p99_ms=100.0, down_p99_ms=20.0)
    hist = get_registry().histogram("serve.router.latency_seconds.interactive")
    before = hist.bucket_counts()
    for v in (0.01, 0.02, 0.5, 0.5, 0.5):
        hist.observe(v)
    delta = [x - y for x, y in zip(hist.bucket_counts(), before)]
    expect = quantiles_from_counts(hist.bounds, delta, (0.99,))[0]
    row = a.step(now=100.0)
    assert row["p99_ms"] == pytest.approx(round(expect * 1e3, 3))
    # consumed window: the next step sees no completions (p99 None)
    assert a.step(now=200.0)["p99_ms"] is None


# ---------------------------------------------------------------------------
# actuation: admission (shed / margin / retries) and batcher (fill-or-flush)
# ---------------------------------------------------------------------------


class _EchoEngine:
    def predict_async(self, images):
        class _Handle:
            def result(_self):
                return images[:, 0, 0, :1]

        return _Handle()

    def predict(self, images):
        return self.predict_async(images).result()


class _FailingEngine:
    """Counts attempts; every dispatch fails (the retry drill)."""

    def __init__(self):
        self.attempts = 0

    def predict_async(self, images):
        self.attempts += 1
        raise InjectedFault("down")

    def predict(self, images):
        return self.predict_async(images)


def _img(val=0.0):
    return np.full((4, 4, 3), float(val), np.float32)


def _policy(level):
    return build_ladder(retry_after_s=2.0)[level]


def test_admission_sheds_brownout_classes_with_retry_after():
    get_registry().reset()
    batcher = PipelinedBatcher(_EchoEngine(), max_batch=1, max_wait_ms=0.0,
                               drain_timeout_s=2.0).start()
    try:
        adm = AdmissionController(batcher, max_retries=0)
        adm.apply_brownout(_policy(3))
        # best_effort: rejected at the door, typed, counted, with the hint
        with pytest.raises(BrownoutShed) as ei:
            adm.submit(_img(), priority="best_effort")
        assert ei.value.retry_after_s == 2.0
        assert _snap("serve.rejected_brownout") == 1
        assert _snap("serve.rejected.best_effort") == 1
        # interactive and batch still serve at L3
        assert adm.submit(_img(5), priority="interactive").result(timeout=5) is not None
        assert adm.submit(_img(5), priority="batch").result(timeout=5) is not None
        # L4 sheds batch too; L0 restores everything
        adm.apply_brownout(_policy(4))
        with pytest.raises(BrownoutShed):
            adm.submit(_img(), priority="batch")
        adm.apply_brownout(_policy(0))
        assert adm.submit(_img(5), priority="best_effort").result(timeout=5) is not None
        assert adm.state()["brownout"]["level"] == 0
    finally:
        batcher.stop()


def test_admission_margin_tightens_deadline_rejection():
    get_registry().reset()
    batcher = PipelinedBatcher(_EchoEngine(), max_batch=1, max_wait_ms=0.0,
                               drain_timeout_s=2.0).start()
    try:
        adm = AdmissionController(batcher, max_retries=0, ewma_alpha=1.0)
        adm.submit(_img(), priority="interactive").result(timeout=5)
        time.sleep(0.05)  # the completion callback records the latency
        base = adm.predicted_wait_s("interactive")
        assert base > 0
        adm.apply_brownout(_policy(5))
        assert adm.predicted_wait_s("interactive") == pytest.approx(
            base * _policy(5).deadline_margin, rel=1e-6)
        # a deadline that clears the base predictor but not the tightened
        # one is rejected at arrival under L5
        deadline_ms = base * 2.0 * 1e3  # 2x base < the 2.5x L5 margin
        with pytest.raises(Exception, match="predicted wait"):
            adm.submit(_img(), priority="interactive", deadline_ms=deadline_ms)
    finally:
        batcher.stop()


def test_admission_survival_mode_disables_retries():
    get_registry().reset()
    eng = _FailingEngine()
    batcher = PipelinedBatcher(eng, max_batch=1, max_wait_ms=0.0,
                               drain_timeout_s=2.0).start()
    try:
        adm = AdmissionController(batcher, max_retries=2, retry_backoff_ms=1.0,
                                  breaker_threshold=100)
        with pytest.raises(InjectedFault):
            adm.submit(_img(), priority="interactive").result(timeout=5)
        time.sleep(0.3)  # let the retry timers run out
        assert eng.attempts == 3  # 1 + max_retries
        retries0 = _snap("serve.retries")
        assert retries0 == 2
        adm.apply_brownout(_policy(5))
        with pytest.raises(InjectedFault):
            adm.submit(_img(), priority="interactive").result(timeout=5)
        time.sleep(0.2)
        assert eng.attempts == 4  # exactly one attempt: no retries at L5
        assert _snap("serve.retries") == retries0
    finally:
        batcher.stop()


def test_batcher_fill_or_flush_skips_linger():
    """With a HUGE linger window, a lone request normally waits ~max_wait_ms
    before dispatch; under fill-or-flush it must dispatch immediately."""
    get_registry().reset()
    batcher = PipelinedBatcher(_EchoEngine(), max_batch=8, max_wait_ms=500.0,
                               drain_timeout_s=2.0).start()
    try:
        batcher.apply_brownout(_policy(2))
        t0 = time.perf_counter()
        batcher.submit(_img(3)).result(timeout=5)
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.25, f"fill-or-flush still lingered ({elapsed:.3f}s)"
        # back at L0 the linger returns (the flag is reversible)
        batcher.apply_brownout(_policy(0))
        t0 = time.perf_counter()
        batcher.submit(_img(3)).result(timeout=5)
        assert time.perf_counter() - t0 >= 0.4
    finally:
        batcher.stop()


# ---------------------------------------------------------------------------
# e2e storm smoke: real HTTP frontend, 3x capacity, brownout on
# ---------------------------------------------------------------------------


class _PacedEngine:
    """Fixed service time per dispatch: a deterministic capacity ceiling
    (batches/s = 1/service_s) so a storm is a storm on any box."""

    def __init__(self, service_s=0.02):
        self.service_s = service_s

    def predict_async(self, images):
        eng = self

        class _Handle:
            def result(_self):
                time.sleep(eng.service_s)
                return images[:, 0, 0, :1]

        return _Handle()

    def predict(self, images):
        return self.predict_async(images).result()


def test_storm_e2e_interactive_holds_while_best_effort_sheds():
    get_registry().reset()
    batcher = PipelinedBatcher(_PacedEngine(0.02), max_batch=4, max_wait_ms=5.0,
                               queue_depth=64, drain_timeout_s=10.0).start()
    admission = AdmissionController(batcher, max_retries=0)
    controller = BrownoutController(
        SignalReader(latency_family="serve.latency_seconds",
                     signal_class="interactive",
                     queue_depth_fn=admission.queued_total),
        (batcher, admission),
        interval_s=0.05,
        # up thresholds sit between the unloaded service time (~25 ms, queue
        # ~0) and the saturated steady state (~100 ms, queue ~12), so the
        # storm trips them on any box; the dead band down to 30 ms / 1
        # queued keeps the ladder from flapping mid-storm
        up_p99_ms=60.0, down_p99_ms=30.0,
        up_queue_depth=5.0, down_queue_depth=1.0,
        hold_up_s=0.15, cooldown_s=0.4,
    ).start()
    frontend = Frontend(admission).start()
    client = ReplicaClient("127.0.0.1", frontend.port, timeout_s=30.0)
    stats = {"interactive": {"ok": 0, "shed": 0, "err": 0},
             "best_effort": {"ok": 0, "shed": 0, "err": 0}}
    lock = threading.Lock()
    stop_t = time.perf_counter() + 2.5
    retry_after_seen = []

    def storm(cls):
        img = _img(1.0)
        while time.perf_counter() < stop_t:
            try:
                client.predict(img, priority=cls, timeout_s=30.0)
                with lock:
                    stats[cls]["ok"] += 1
            except ClientHTTPError as e:
                with lock:
                    if e.tag == "brownout":
                        stats[cls]["shed"] += 1
                        retry_after_seen.append(e.retry_after)
                    else:
                        stats[cls]["err"] += 1
                time.sleep(0.01)

    # ~3x capacity: capacity is 4 rows / 20 ms = 200 rows/s; 12 closed-loop
    # clients with sub-ms think time push the queue well past it
    threads = [threading.Thread(target=storm, args=(cls,), daemon=True)
               for cls in ("interactive", "best_effort") for _ in range(6)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        peak = max(r["level"] for r in controller.trace)
        assert peak >= 3, f"ladder never reached best_effort shedding (peak L{peak})"
        be = stats["best_effort"]
        assert be["shed"] >= 1, "best_effort never shed at the door"
        assert all(ra is not None and ra > 0 for ra in retry_after_seen), (
            "brownout sheds must carry Retry-After")
        ia = stats["interactive"]
        total_i = ia["ok"] + ia["shed"] + ia["err"]
        assert total_i > 0 and ia["ok"] / total_i >= 0.9, ia
        assert ia["shed"] == 0  # interactive is never brownout-shed
        # after the storm the ladder must fully recover (idle windows are
        # relaxed; one level per 0.4s cooldown from at most L5)
        deadline = time.monotonic() + 15
        while controller.level > 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert controller.level == 0, "ladder never recovered to L0 after the storm"
        up = _snap("serve.brownout_transitions.up")
        down = _snap("serve.brownout_transitions.down")
        assert up == down >= 3
        assert _snap("serve.brownout_level") == 0
    finally:
        controller.stop()
        frontend.stop()
        batcher.stop()
        client.close()
