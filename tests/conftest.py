"""Test harness: run everything on CPU with 8 fake devices.

This is the TPU-world "fake backend" (SURVEY.md §4.2): multi-chip logic
(psum gradient allreduce, SyncBN, sharded updates) is exercised on an
8-device host-platform mesh with no TPU present.  Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
