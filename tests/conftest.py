"""Test harness: run everything on CPU with 8 fake devices.

This is the TPU-world "fake backend" (SURVEY.md §4.2): multi-chip logic
(psum gradient allreduce, SyncBN, sharded updates) is exercised on an
8-device host-platform mesh with no TPU present.  Must run before jax import.
"""

import os

# Force-set (not setdefault): the sandbox exports JAX_PLATFORMS for the real
# TPU tunnel, but tests must be deterministic f32 CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
# Drop any pre-set device-count flag and force 8 (a foreign value would make
# the device-count assert below kill the whole session).
flags = [f for f in os.environ.get("XLA_FLAGS", "").split() if "xla_force_host_platform_device_count" not in f]
os.environ["XLA_FLAGS"] = " ".join(flags + ["--xla_force_host_platform_device_count=8"])
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# The sandbox's sitecustomize imports jax at interpreter startup (axon PJRT
# registration), which freezes jax_platforms before this file runs — so the
# env var alone is not enough; override the live config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert jax.default_backend() == "cpu"
assert len(jax.devices()) == 8, jax.devices()
