"""Survivable training (the robustness PR's training twin of the serve
chaos suite): the non-finite step guard's device-side rollback, the host
accounting + train_health.json bound, crash-consistent restore fallback
through corrupt checkpoints, and the SIGTERM kill-and-resume e2e.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.ckpt.manager import CheckpointManager
from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import GuardConfig, config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.obs import registry as obs_registry
from yet_another_mobilenet_series_tpu.parallel import mesh as mesh_lib
from yet_another_mobilenet_series_tpu.train import guard as guard_lib
from yet_another_mobilenet_series_tpu.train import optim, schedules, steps
from yet_another_mobilenet_series_tpu.utils.logging import Logger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# step guard: device-side skip-and-rollback
# ---------------------------------------------------------------------------


def _tiny_setup():
    cfg = config_from_dict({
        "model": {"arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
                  "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}]},
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 0.0},
        "schedule": {"schedule": "constant", "base_lr": 0.05,
                     "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {"compute_dtype": "float32"},
    })
    net = get_model(cfg.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
    step_fn = jax.jit(guard_lib.wrap_step_fn(steps.make_train_step(net, cfg, opt, lr_fn)))
    return ts, step_fn


def test_guard_skips_nonfinite_step_and_rolls_back():
    """A NaN batch must cost exactly one SKIPPED step: every TrainState field
    except the step counter is bit-identical to the pre-step state, and the
    next good step trains normally from it."""
    ts, step_fn = _tiny_setup()
    rng = jax.random.PRNGKey(42)
    good = {"image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)),
            "label": jnp.arange(8) % 4}
    poisoned = dict(good, image=good["image"].at[0].set(jnp.nan))

    ts_bad, m_bad = step_fn(ts, poisoned, rng)
    assert float(m_bad["skipped"]) == 1.0
    assert float(m_bad["finite"]) == 0.0
    # rollback: params/opt/EMA bit-identical to the pre-step state
    for field in ("params", "state", "opt_state", "ema_params", "ema_state"):
        for a, b in zip(jax.tree.leaves(getattr(ts, field)), jax.tree.leaves(getattr(ts_bad, field))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=field)
    # ...but the step counter advanced (data order / LR stay aligned)
    assert int(ts_bad.step) == int(ts.step) + 1

    ts_good, m_good = step_fn(ts_bad, good, rng)
    assert float(m_good["skipped"]) == 0.0 and float(m_good["finite"]) == 1.0
    diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), ts_good.params, ts_bad.params)
    assert max(jax.tree.leaves(diffs)) > 0  # the good step actually updated


def test_step_guard_budget_and_health_dump(tmp_path):
    """Host accounting: skips are counted at the check cadence; exceeding
    max_skipped_steps raises TrainHealthError AFTER writing the
    train_health.json post-mortem."""
    reg = obs_registry.get_registry()
    before = reg.snapshot().get("train.skipped_steps", 0.0)
    g = guard_lib.StepGuard(GuardConfig(enable=True, max_skipped_steps=2), str(tmp_path))
    for step_i, bad in ((1, 0.0), (2, 1.0), (3, 1.0)):
        g.observe(step_i, {"skipped": np.float32(bad)})
    g.check(3)  # 2 skips == budget: survives
    assert g.skipped_total == 2
    assert reg.snapshot()["train.skipped_steps"] == before + 2
    assert g.info()["recent_skipped_steps"] == [2, 3]

    g.observe(4, {"skipped": np.float32(1.0)})
    with pytest.raises(guard_lib.TrainHealthError, match="max_skipped_steps"):
        g.check(4)
    report = json.loads((tmp_path / guard_lib.HEALTH_REPORT_NAME).read_text())
    assert report["skipped_total"] == 3 and report["max_skipped_steps"] == 2
    assert report["recent_skipped_steps"] == [2, 3, 4]
    assert "train.skipped_steps" in report["registry"]


def _cli_cfg(tmp_path, **over):
    d = {
        "name": "preempt",
        "model": {"arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
                  "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}]},
        "data": {"dataset": "fake", "image_size": 16, "fake_train_size": 256,
                 "fake_eval_size": 32, "fake_num_classes": 4},
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 0.0},
        "schedule": {"schedule": "constant", "base_lr": 0.05,
                     "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": False},
        "train": {"batch_size": 16, "eval_batch_size": 16, "epochs": 1,
                  "log_every": 2, "compute_dtype": "float32",
                  "log_dir": str(tmp_path), "eval_every_epochs": 0.0},
        "dist": {"num_devices": 8},
    }
    for k, v in over.items():
        cur = d
        ks = k.split(".")
        for kk in ks[:-1]:
            cur = cur.setdefault(kk, {})
        cur[ks[-1]] = v
    return config_from_dict(d)


def test_guard_and_faults_wired_through_cli(tmp_path):
    """End-to-end in-process: train.faults poisons one step, train.guard
    skips it, and the run still completes with the skip counted."""
    reg = obs_registry.get_registry()
    before = reg.snapshot().get("train.skipped_steps", 0.0)
    cfg = _cli_cfg(
        tmp_path,
        **{"train.guard.enable": True, "train.guard.max_skipped_steps": 3,
           "train.faults.enable": True, "train.faults.nan_at_steps": [3]},
    )
    result = cli_train.run(cfg)
    assert result["epoch"] == pytest.approx(1.0)
    snap = reg.snapshot()
    assert snap["train.skipped_steps"] == before + 1
    assert snap["train.faults.nan_steps"] >= 1
    assert not os.path.exists(tmp_path / guard_lib.HEALTH_REPORT_NAME)


def test_guard_budget_aborts_run_with_health_report(tmp_path):
    """Every step NaN (injected) with a budget of 2: the run must abort with
    TrainHealthError and leave train_health.json."""
    cfg = _cli_cfg(
        tmp_path,
        **{"train.guard.enable": True, "train.guard.max_skipped_steps": 2,
           "train.faults.enable": True,
           "train.faults.nan_at_steps": list(range(1, 17))},
    )
    with pytest.raises(guard_lib.TrainHealthError):
        cli_train.run(cfg)
    report = json.loads((tmp_path / guard_lib.HEALTH_REPORT_NAME).read_text())
    assert report["skipped_total"] > 2


# ---------------------------------------------------------------------------
# crash-consistent restore: fallback through corrupt checkpoints
# ---------------------------------------------------------------------------


def _two_checkpoints(tmp_path):
    """Two REAL checkpoints (steps 1 and 2) through the cli Trainer, tagged
    via extra so the test can see which one a restore picked."""
    cfg = _cli_cfg(tmp_path)
    mesh = mesh_lib.make_mesh(8)
    log = Logger(enabled=False)
    net = get_model(cfg.model, cfg.data.image_size)
    trainer = cli_train.Trainer(cfg, net, mesh, log)
    ts = trainer.init_state(jax.random.PRNGKey(0))
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
    for step in (1, 2):
        ts = ts.replace(step=jnp.asarray(step, jnp.int32))
        mgr.save(step, net, jax.device_get(trainer.checkpoint_view(ts)),
                 extra={"tag": f"step{step}", "epoch": float(step)})
        mgr.wait()
    return cfg, mesh, log, mgr


def _fallbacks():
    return obs_registry.get_registry().snapshot().get("ckpt.restore_fallbacks", 0.0)


def test_restore_falls_back_on_corrupt_spec_sidecar(tmp_path):
    """Satellite: a corrupted/missing JSON spec sidecar on the latest step
    must fall back to the previous step, counted."""
    cfg, mesh, log, mgr = _two_checkpoints(tmp_path)
    for meta in glob.glob(str(tmp_path / "ck" / "2" / "meta" / "*")):
        with open(meta, "w") as f:
            f.write("{ this is not json")
    before = _fallbacks()
    trainer, ts, extra = cli_train._restore(mgr, cfg, mesh, log)
    assert extra["tag"] == "step1" and int(ts.step) == 1
    assert _fallbacks() == before + 1
    mgr.close()


def test_restore_falls_back_on_truncated_tree_item(tmp_path):
    """Satellite: a truncated tree item (torn write) on the latest step must
    fall back to the previous step, counted."""
    cfg, mesh, log, mgr = _two_checkpoints(tmp_path)
    data_files = glob.glob(str(tmp_path / "ck" / "2" / "tree" / "d" / "*"))
    assert data_files
    for f in data_files:
        with open(f, "rb") as fh:
            b = fh.read()
        with open(f, "wb") as fh:
            fh.write(b[: max(1, len(b) // 2)])
    before = _fallbacks()
    trainer, ts, extra = cli_train._restore(mgr, cfg, mesh, log)
    assert extra["tag"] == "step1" and int(ts.step) == 1
    assert _fallbacks() == before + 1
    mgr.close()


def test_restore_falls_back_on_digest_mismatch(tmp_path):
    """Corruption Orbax's own storage checks cannot see (bytes valid, values
    wrong — simulated by rewriting the recorded digest) must still be caught
    by the sidecar verification and fall back."""
    from yet_another_mobilenet_series_tpu.ckpt import manager as mgr_mod

    cfg, mesh, log, mgr = _two_checkpoints(tmp_path)
    digest_path = tmp_path / "ck" / mgr_mod.DIGEST_NAME
    index = json.loads(digest_path.read_text())
    assert set(index) == {"1", "2"}
    index["2"]["params"] = "0" * 64
    digest_path.write_text(json.dumps(index))
    before = _fallbacks()
    trainer, ts, extra = cli_train._restore(mgr, cfg, mesh, log)
    assert extra["tag"] == "step1" and int(ts.step) == 1
    assert _fallbacks() == before + 1
    assert obs_registry.get_registry().snapshot()["ckpt.integrity_failures"] >= 1
    mgr.close()


def test_restore_raises_when_every_candidate_is_corrupt(tmp_path):
    """All candidates corrupt: resume must die loudly (never silently restart
    from zero over a directory full of checkpoints)."""
    cfg, mesh, log, mgr = _two_checkpoints(tmp_path)
    for step in (1, 2):
        for meta in glob.glob(str(tmp_path / "ck" / str(step) / "meta" / "*")):
            with open(meta, "w") as f:
                f.write("garbage")
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        cli_train._restore(mgr, cfg, mesh, log)
    mgr.close()


# ---------------------------------------------------------------------------
# kill-and-resume e2e (real SIGTERM from outside, subprocess)
# ---------------------------------------------------------------------------


def test_sigterm_kill_and_resume_e2e(tmp_path):
    """The headline proof: an externally SIGTERM'd training subprocess exits
    CLEANLY (rc 0) with a synchronous final checkpoint and a resume marker;
    a resumed run continues from that step — same log dir, no
    restart-from-zero — and finishes."""
    log_dir = tmp_path / "run"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(flags + ["--xla_force_host_platform_device_count=8"])
    overrides = [
        "data.dataset=fake", "data.image_size=16", "data.fake_train_size=256",
        "data.fake_eval_size=32", "data.fake_num_classes=4",
        "model.arch=mobilenet_v2", "model.num_classes=4", "model.dropout=0.0",
        "model.block_specs=[{t: 2, c: 8, n: 1, s: 2}]",
        "optim.optimizer=sgd", "optim.momentum=0.9", "optim.weight_decay=0.0",
        "schedule.schedule=constant", "schedule.base_lr=0.05",
        "schedule.scale_by_batch=false", "schedule.warmup_epochs=0.0",
        "ema.enable=false", "train.batch_size=16", "train.eval_batch_size=16",
        "train.epochs=50", "train.log_every=1", "train.compute_dtype=float32",
        "train.eval_every_epochs=0", "train.checkpoint_every_epochs=0",
        f"train.log_dir={log_dir}", "dist.num_devices=8",
    ]
    proc = subprocess.Popen(
        [sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.train"] + overrides,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=REPO, env=env,
    )
    try:
        # wait until training demonstrably made progress (≥2 metric rows),
        # then deliver the preemption signal mid-epoch
        metrics_path = log_dir / "metrics.jsonl"
        deadline = time.time() + 180
        while time.time() < deadline:
            try:
                if len(metrics_path.read_text().splitlines()) >= 2:
                    break
            except OSError:
                pass
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(f"training died before the kill: {err[-800:]}")
            time.sleep(0.2)
        else:
            pytest.fail("training never produced metric rows")
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, (out[-500:], err[-500:])
    assert "preemption checkpoint" in out

    marker = json.loads((log_dir / cli_train.PREEMPT_MARKER_NAME).read_text())
    killed_step = int(marker["step"])
    assert killed_step > 0 and marker["reason"] == "SIGTERM"
    # the synchronous final save is restorable at exactly the marker step
    mgr = CheckpointManager(str(log_dir / "ckpt"), async_save=False)
    assert mgr.latest_step() == killed_step
    mgr.close()

    # resume in-process: continues from the killed step to completion. One
    # full epoch is 16 steps; the kill landed well inside it.
    resume_epochs = max(1.0, (killed_step + 4) / 16.0)
    cfg = _cli_cfg(log_dir, **{"train.epochs": resume_epochs})
    result = cli_train.run(cfg)
    assert "preempted" not in result
    assert result["epoch"] >= marker["epoch"]
    # the marker is consumed by the successful resume
    assert not os.path.exists(log_dir / cli_train.PREEMPT_MARKER_NAME)
