"""Acceptance #1 at FULL scale (VERDICT r2 next-round #4): a MobileNetV2-1.0
torch state_dict (torchvision layout, built by the same generator the unit
tests use), saved as a real .pth, evaluated through the REAL eval CLI on a
~200-image set of REAL JPEGs — importer + JPEG decode + eval transform + eval
counting welded into one executed path, through BOTH input pipelines
(dataset=folder/native C++ loader and the TFRecord/tf.data path).

Ground truth: each image's label is the torch model's own argmax computed
through an INDEPENDENT decode chain (PIL decode + torch bilinear resize +
center crop + normalize). The torch model's top-1 against these labels is
1.0 by construction, so our CLI's top-1 measures end-to-end agreement of the
import and the full input pipeline; small decoder/resize implementation
differences may flip near-tie argmaxes, hence the tolerance.

JPEGs are saved 4:4:4 (subsampling=0) from smooth synthetic content so
libjpeg chroma-upsampling differences between the three decoders (PIL, tf,
native libjpeg) stay sub-LSB.
"""

import os

import numpy as np
import pytest

import torch
import torch.nn.functional as F
from PIL import Image

from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import DataConfig, ModelConfig, config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model

from test_torch_import import TorchTinyMBV2

N_IMAGES = 200
# the SAME normalization the eval pipelines read from config — hardcoded
# copies here would silently diverge if the defaults ever changed
MEAN = tuple(DataConfig().mean)
STD = tuple(DataConfig().std)

pytestmark = pytest.mark.slow


def _make_jpegs(root, n, seed=0):
    """n smooth random JPEGs with varied sizes (exercises resize-shorter)."""
    os.makedirs(root, exist_ok=True)
    rs = np.random.RandomState(seed)
    paths = []
    for i in range(n):
        h, w = int(rs.randint(240, 321)), int(rs.randint(240, 321))
        low = rs.uniform(0, 255, (8, 8, 3)).astype(np.uint8)
        img = Image.fromarray(low).resize((w, h), Image.BICUBIC)
        p = os.path.join(root, f"img_{i:04d}.jpg")
        img.save(p, quality=95, subsampling=0)
        paths.append(p)
    return paths


def _torch_preprocess(path, eval_resize=256, crop=224):
    """PIL decode + torch bilinear resize-shorter + center crop + normalize —
    the reference Resize(256)/CenterCrop(224) recipe (SURVEY.md §3.3),
    matching data/pipeline.py:_decode_center_crop's rounding."""
    img = np.asarray(Image.open(path).convert("RGB"), np.float32)
    h, w = img.shape[:2]
    ratio = eval_resize / min(h, w)
    rh, rw = int(round(h * ratio)), int(round(w * ratio))
    t = torch.from_numpy(img.transpose(2, 0, 1))[None]
    t = F.interpolate(t, size=(rh, rw), mode="bilinear", align_corners=False)
    top, left = (rh - crop) // 2, (rw - crop) // 2
    t = t[..., top : top + crop, left : left + crop] / 255.0
    mean = torch.tensor(MEAN)[None, :, None, None]
    std = torch.tensor(STD)[None, :, None, None]
    return (t - mean) / std


@pytest.fixture(scope="module")
def mbv2_fixture(tmp_path_factory):
    """Full MobileNetV2-1.0, its .pth, the labeled ImageFolder tree, and the
    torch-side predictions — shared by the folder-path and TFRecord tests."""
    tmp = tmp_path_factory.mktemp("mbv2_acceptance")
    net = get_model(ModelConfig(arch="mobilenet_v2", dropout=0.0), image_size=224)
    torch.manual_seed(0)
    tm = TorchTinyMBV2(net, 1000)
    for m in tm.modules():
        if isinstance(m, torch.nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn_like(m.running_mean) * 0.3)
            m.running_var.copy_(torch.rand_like(m.running_var) * 2 + 0.5)
            m.weight.data.copy_(torch.rand_like(m.weight) + 0.5)
            m.bias.data.copy_(torch.randn_like(m.bias) * 0.2)
    tm.eval()
    pth = str(tmp / "mobilenet_v2_full.pth")
    torch.save(tm.state_dict(), pth)

    raw = str(tmp / "raw")
    paths = _make_jpegs(raw, N_IMAGES)
    preds = []
    with torch.no_grad():
        for i in range(0, N_IMAGES, 25):
            batch = torch.cat([_torch_preprocess(p) for p in paths[i : i + 25]])
            preds.extend(tm(batch).argmax(1).tolist())

    # ImageFolder tree with ALL 1000 class dirs (most empty) so sorted-dir
    # rank == class id and folder labels live in the net's own label space
    val_root = str(tmp / "data" / "val")
    for c in range(1000):
        os.makedirs(os.path.join(val_root, f"{c:04d}"), exist_ok=True)
    for p, cls in zip(paths, preds):
        os.link(p, os.path.join(val_root, f"{cls:04d}", os.path.basename(p)))
    return {"pth": pth, "data_root": str(tmp / "data"), "preds": preds, "tmp": tmp}


def _eval_cfg(fix, log_dir, **data_over):
    data = {"image_size": 224, "eval_resize": 256, "num_eval_examples": N_IMAGES}
    data.update(data_over)
    return config_from_dict({
        "name": "mbv2_acceptance",
        "model": {"arch": "mobilenet_v2", "dropout": 0.0},
        "data": data,
        "train": {
            "test_only": True,
            "torch_pretrained": fix["pth"],
            "eval_batch_size": 50,
            "compute_dtype": "float32",
            "log_dir": str(log_dir),
        },
        # acceptance #1 is single-process eval (SURVEY.md §3.3)
        "dist": {"num_devices": 1},
    })


def test_full_scale_bn_mode_prediction_agreement(mbv2_fixture):
    """The PROFILE.md round-3 decision rule's 'top-1-parity argument' for the
    perf bn_modes (VERDICT r3 #5), at full scale: the imported MBV2's
    predictions on the 200 real JPEGs, forwarded in bfloat16 (the production
    training dtype — the only regime where `compute` differs from `folded`),
    must agree with the exact-mode predictions to within the same near-tie
    tolerance the acceptance tests grant decoder differences. This test is
    the evidence `scripts/tpu_watch.py --allow-compute` cites: a >3% compute
    win on hardware is adoptable because its forward perturbation is below
    the noise the fixture already tolerates.

    `fused_vjp` shares folded's eval expression (ops/layers.py BatchNorm
    .apply) and its train-mode gradients are pinned elsewhere
    (test_ops.py test_batchnorm_fused_vjp_*); the training-dynamics half of
    the compute argument is test_train.py::test_bn_variants_converge_identically."""
    from yet_another_mobilenet_series_tpu.ckpt.torch_import import load_torch_checkpoint

    net = get_model(ModelConfig(arch="mobilenet_v2", dropout=0.0), image_size=224)
    params, state = load_torch_checkpoint(mbv2_fixture["pth"], net)

    raw = str(mbv2_fixture["tmp"] / "raw")
    paths = sorted(os.path.join(raw, f) for f in os.listdir(raw) if f.endswith(".jpg"))
    assert len(paths) == N_IMAGES
    # 100 of the 200 fixture images: 6 full bf16 predict passes dominate the
    # suite's slowest test (554 s measured round 5) and the 0.95/0.98
    # agreement thresholds are equally meaningful at n=100 (granularity 1%);
    # the eval-CLI tests below still consume all 200
    paths = paths[::2]
    # identical inputs for every mode: the torch-side preprocessing chain
    imgs = np.concatenate(
        [_torch_preprocess(p).numpy() for p in paths]
    ).transpose(0, 2, 3, 1)  # NHWC

    import jax

    def predict(bn_mode, conv1x1_dot, compute_dtype="bfloat16"):
        import jax.numpy as jnp

        dt = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[compute_dtype]

        @jax.jit
        def fwd(x):
            logits, _ = net.apply(
                params, state, x.astype(dt), train=False, compute_dtype=dt,
                bn_mode=bn_mode, conv1x1_dot=conv1x1_dot,
            )
            return jnp.argmax(logits, -1)

        return np.concatenate(
            [np.asarray(fwd(imgs[i : i + 50])) for i in range(0, len(imgs), 50)]
        )

    base = predict("exact", False)
    # sanity: bf16 exact agrees with the torch-side f32 ground truth to the
    # acceptance tolerance (bf16 rounding ~ decoder noise, both sub-percent)
    assert np.mean(base == np.asarray(mbv2_fixture["preds"])[::2]) >= 0.95

    agreement = {}
    for mode, dot in [("folded", False), ("fused_vjp", False), ("exact", True),
                      ("compute", False), ("compute", True)]:
        agreement[(mode, dot)] = float(np.mean(predict(mode, dot) == base))
    # folded/fused_vjp/dot are re-association/lowering changes: sub-bf16-ulp
    for key in [("folded", False), ("fused_vjp", False), ("exact", True)]:
        assert agreement[key] >= 0.98, agreement
    # compute (bf16 FMA scale/bias) is the gated mode: its flips must stay
    # within the near-tie band the fixture grants decoder differences
    assert agreement[("compute", False)] >= 0.95, agreement
    assert agreement[("compute", True)] >= 0.95, agreement


def test_full_scale_eval_folder_native(mbv2_fixture, tmp_path):
    cfg = _eval_cfg(
        mbv2_fixture, tmp_path,
        dataset="folder", loader="native", data_dir=mbv2_fixture["data_root"], val_split="val",
    )
    result = cli_train.run(cfg)
    assert result["n"] == N_IMAGES  # every real example counted exactly once
    # torch's own top-1 on these labels is 1.0 by construction; ours may lose
    # a few near-tie argmaxes to decoder/resize implementation differences
    assert result["top1"] >= 0.95, result
    mbv2_fixture["native_top1"] = result["top1"]


def test_full_scale_eval_tfrecord(mbv2_fixture, tmp_path):
    import subprocess
    import sys

    tfdir = str(mbv2_fixture["tmp"] / "tfrecords")
    script = os.path.join(os.path.dirname(__file__), "..", "scripts", "imagefolder_to_tfrecords.py")
    subprocess.run(
        [sys.executable, script, "--src", os.path.join(mbv2_fixture["data_root"], "val"),
         "--dst", tfdir, "--split", "validation", "--shards", "2"],
        check=True, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    cfg = _eval_cfg(
        mbv2_fixture, tmp_path,
        dataset="imagenet", loader="tfdata", data_dir=tfdir, val_split="validation",
    )
    result = cli_train.run(cfg)
    assert result["n"] == N_IMAGES
    assert result["top1"] >= 0.95, result
    if "native_top1" in mbv2_fixture:
        # the two pipelines decode the same JPEGs: their top-1s must agree
        # to within a couple of near-tie flips
        assert abs(result["top1"] - mbv2_fixture["native_top1"]) <= 0.02


def test_real_imagenet_bn_mode_top1_delta():
    """Env-gated REAL-DATA upgrade of the compute-parity gate (VERDICT r4
    next #7): the synthetic fixture above argues within decoder-noise
    tolerance; the moment real data exists in the sandbox, point

        YAMT_IMAGENET_VAL_DIR  at an ImageFolder val tree (val/<class>/*.JPEG,
                               sorted-dir rank == class id — the torchvision
                               convention), and
        YAMT_MBV2_PTH          at a real MobileNetV2 torchvision state_dict,

    and this becomes a true top-1 delta measurement: each bn_mode's accuracy
    on (up to YAMT_REAL_EVAL_N, default 1000) real images vs exact-mode bf16.
    Skipped when the env is absent — no sandbox ImageNet exists as of round 5."""
    val_dir = os.environ.get("YAMT_IMAGENET_VAL_DIR")
    pth = os.environ.get("YAMT_MBV2_PTH")
    if not (val_dir and os.path.isdir(val_dir) and pth and os.path.exists(pth)):
        pytest.skip("set YAMT_IMAGENET_VAL_DIR + YAMT_MBV2_PTH to run on real data")
    n_max = int(os.environ.get("YAMT_REAL_EVAL_N", "1000"))

    from yet_another_mobilenet_series_tpu.ckpt.torch_import import load_torch_checkpoint

    net = get_model(ModelConfig(arch="mobilenet_v2", dropout=0.0), image_size=224)
    params, state = load_torch_checkpoint(pth, net)

    classes = sorted(d for d in os.listdir(val_dir) if os.path.isdir(os.path.join(val_dir, d)))
    samples = []
    for label, cls in enumerate(classes):
        for f in sorted(os.listdir(os.path.join(val_dir, cls))):
            samples.append((os.path.join(val_dir, cls, f), label))
    # deterministic spread across classes rather than the first k classes
    rs = np.random.RandomState(0)
    rs.shuffle(samples)
    samples = samples[:n_max]
    assert samples, f"no images under {val_dir}"

    imgs = np.concatenate([_torch_preprocess(p).numpy() for p, _ in samples]).transpose(0, 2, 3, 1)
    labels = np.asarray([l for _, l in samples])

    import jax
    import jax.numpy as jnp

    def top1(bn_mode, conv1x1_dot):
        @jax.jit
        def fwd(x):
            logits, _ = net.apply(params, state, x.astype(jnp.bfloat16), train=False,
                                  compute_dtype=jnp.bfloat16,
                                  bn_mode=bn_mode, conv1x1_dot=conv1x1_dot)
            return jnp.argmax(logits, -1)

        preds = np.concatenate([np.asarray(fwd(imgs[i:i + 50])) for i in range(0, len(imgs), 50)])
        return float(np.mean(preds == labels))

    base = top1("exact", False)
    assert base > 0.6, f"real MBV2 should clear 60% top-1; got {base} (wrong .pth?)"
    deltas = {}
    for mode, dot in [("folded", False), ("fused_vjp", False), ("exact", True),
                      ("compute", False), ("compute", True)]:
        deltas[(mode, dot)] = top1(mode, dot) - base
    # parity-safe modes: within pure-noise band; compute family: the real
    # contract number — adopt only if the true top-1 cost is negligible
    for key in [("folded", False), ("fused_vjp", False), ("exact", True)]:
        assert abs(deltas[key]) <= 0.002, deltas
    assert abs(deltas[("compute", False)]) <= 0.005, deltas
    assert abs(deltas[("compute", True)]) <= 0.005, deltas
