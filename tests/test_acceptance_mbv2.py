"""Acceptance #1 at FULL scale (VERDICT r2 next-round #4): a MobileNetV2-1.0
torch state_dict (torchvision layout, built by the same generator the unit
tests use), saved as a real .pth, evaluated through the REAL eval CLI on a
~200-image set of REAL JPEGs — importer + JPEG decode + eval transform + eval
counting welded into one executed path, through BOTH input pipelines
(dataset=folder/native C++ loader and the TFRecord/tf.data path).

Ground truth: each image's label is the torch model's own argmax computed
through an INDEPENDENT decode chain (PIL decode + torch bilinear resize +
center crop + normalize). The torch model's top-1 against these labels is
1.0 by construction, so our CLI's top-1 measures end-to-end agreement of the
import and the full input pipeline; small decoder/resize implementation
differences may flip near-tie argmaxes, hence the tolerance.

JPEGs are saved 4:4:4 (subsampling=0) from smooth synthetic content so
libjpeg chroma-upsampling differences between the three decoders (PIL, tf,
native libjpeg) stay sub-LSB.
"""

import os

import numpy as np
import pytest

import torch
import torch.nn.functional as F
from PIL import Image

from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import DataConfig, ModelConfig, config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model

from test_torch_import import TorchTinyMBV2

N_IMAGES = 200
# the SAME normalization the eval pipelines read from config — hardcoded
# copies here would silently diverge if the defaults ever changed
MEAN = tuple(DataConfig().mean)
STD = tuple(DataConfig().std)

pytestmark = pytest.mark.slow


def _make_jpegs(root, n, seed=0):
    """n smooth random JPEGs with varied sizes (exercises resize-shorter)."""
    os.makedirs(root, exist_ok=True)
    rs = np.random.RandomState(seed)
    paths = []
    for i in range(n):
        h, w = int(rs.randint(240, 321)), int(rs.randint(240, 321))
        low = rs.uniform(0, 255, (8, 8, 3)).astype(np.uint8)
        img = Image.fromarray(low).resize((w, h), Image.BICUBIC)
        p = os.path.join(root, f"img_{i:04d}.jpg")
        img.save(p, quality=95, subsampling=0)
        paths.append(p)
    return paths


def _torch_preprocess(path, eval_resize=256, crop=224):
    """PIL decode + torch bilinear resize-shorter + center crop + normalize —
    the reference Resize(256)/CenterCrop(224) recipe (SURVEY.md §3.3),
    matching data/pipeline.py:_decode_center_crop's rounding."""
    img = np.asarray(Image.open(path).convert("RGB"), np.float32)
    h, w = img.shape[:2]
    ratio = eval_resize / min(h, w)
    rh, rw = int(round(h * ratio)), int(round(w * ratio))
    t = torch.from_numpy(img.transpose(2, 0, 1))[None]
    t = F.interpolate(t, size=(rh, rw), mode="bilinear", align_corners=False)
    top, left = (rh - crop) // 2, (rw - crop) // 2
    t = t[..., top : top + crop, left : left + crop] / 255.0
    mean = torch.tensor(MEAN)[None, :, None, None]
    std = torch.tensor(STD)[None, :, None, None]
    return (t - mean) / std


@pytest.fixture(scope="module")
def mbv2_fixture(tmp_path_factory):
    """Full MobileNetV2-1.0, its .pth, the labeled ImageFolder tree, and the
    torch-side predictions — shared by the folder-path and TFRecord tests."""
    tmp = tmp_path_factory.mktemp("mbv2_acceptance")
    net = get_model(ModelConfig(arch="mobilenet_v2", dropout=0.0), image_size=224)
    torch.manual_seed(0)
    tm = TorchTinyMBV2(net, 1000)
    for m in tm.modules():
        if isinstance(m, torch.nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn_like(m.running_mean) * 0.3)
            m.running_var.copy_(torch.rand_like(m.running_var) * 2 + 0.5)
            m.weight.data.copy_(torch.rand_like(m.weight) + 0.5)
            m.bias.data.copy_(torch.randn_like(m.bias) * 0.2)
    tm.eval()
    pth = str(tmp / "mobilenet_v2_full.pth")
    torch.save(tm.state_dict(), pth)

    raw = str(tmp / "raw")
    paths = _make_jpegs(raw, N_IMAGES)
    preds = []
    with torch.no_grad():
        for i in range(0, N_IMAGES, 25):
            batch = torch.cat([_torch_preprocess(p) for p in paths[i : i + 25]])
            preds.extend(tm(batch).argmax(1).tolist())

    # ImageFolder tree with ALL 1000 class dirs (most empty) so sorted-dir
    # rank == class id and folder labels live in the net's own label space
    val_root = str(tmp / "data" / "val")
    for c in range(1000):
        os.makedirs(os.path.join(val_root, f"{c:04d}"), exist_ok=True)
    for p, cls in zip(paths, preds):
        os.link(p, os.path.join(val_root, f"{cls:04d}", os.path.basename(p)))
    return {"pth": pth, "data_root": str(tmp / "data"), "preds": preds, "tmp": tmp}


def _eval_cfg(fix, log_dir, **data_over):
    data = {"image_size": 224, "eval_resize": 256, "num_eval_examples": N_IMAGES}
    data.update(data_over)
    return config_from_dict({
        "name": "mbv2_acceptance",
        "model": {"arch": "mobilenet_v2", "dropout": 0.0},
        "data": data,
        "train": {
            "test_only": True,
            "torch_pretrained": fix["pth"],
            "eval_batch_size": 50,
            "compute_dtype": "float32",
            "log_dir": str(log_dir),
        },
        # acceptance #1 is single-process eval (SURVEY.md §3.3)
        "dist": {"num_devices": 1},
    })


def test_full_scale_eval_folder_native(mbv2_fixture, tmp_path):
    cfg = _eval_cfg(
        mbv2_fixture, tmp_path,
        dataset="folder", loader="native", data_dir=mbv2_fixture["data_root"], val_split="val",
    )
    result = cli_train.run(cfg)
    assert result["n"] == N_IMAGES  # every real example counted exactly once
    # torch's own top-1 on these labels is 1.0 by construction; ours may lose
    # a few near-tie argmaxes to decoder/resize implementation differences
    assert result["top1"] >= 0.95, result
    mbv2_fixture["native_top1"] = result["top1"]


def test_full_scale_eval_tfrecord(mbv2_fixture, tmp_path):
    import subprocess
    import sys

    tfdir = str(mbv2_fixture["tmp"] / "tfrecords")
    script = os.path.join(os.path.dirname(__file__), "..", "scripts", "imagefolder_to_tfrecords.py")
    subprocess.run(
        [sys.executable, script, "--src", os.path.join(mbv2_fixture["data_root"], "val"),
         "--dst", tfdir, "--split", "validation", "--shards", "2"],
        check=True, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    cfg = _eval_cfg(
        mbv2_fixture, tmp_path,
        dataset="imagenet", loader="tfdata", data_dir=tfdir, val_split="validation",
    )
    result = cli_train.run(cfg)
    assert result["n"] == N_IMAGES
    assert result["top1"] >= 0.95, result
    if "native_top1" in mbv2_fixture:
        # the two pipelines decode the same JPEGs: their top-1s must agree
        # to within a couple of near-tie flips
        assert abs(result["top1"] - mbv2_fixture["native_top1"]) <= 0.02
