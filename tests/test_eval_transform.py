"""Golden-image test of the tf.data eval transform (SURVEY.md §4.3):
resize-shorter-side + center-crop + normalize vs an independent PIL
reference. Resamplers differ slightly (tf bilinear vs PIL), so geometry is
asserted exactly (via a structured gradient image) and intensities within a
small tolerance."""

import io
import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image

import tensorflow as tf

from yet_another_mobilenet_series_tpu.config import DataConfig
from yet_another_mobilenet_series_tpu.data import pipeline as data_lib


def _make_jpeg(w, h):
    # smooth two-axis gradient: sensitive to crop offsets and resize scale,
    # tolerant to resampler differences
    x = np.linspace(0, 255, w, dtype=np.float32)[None, :, None]
    y = np.linspace(0, 255, h, dtype=np.float32)[:, None, None]
    arr = np.concatenate([np.broadcast_to(x, (h, w, 1)), np.broadcast_to(y, (h, w, 1)), np.full((h, w, 1), 128.0)], -1)
    img = Image.fromarray(arr.astype(np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=97)
    return buf.getvalue(), img


def _pil_reference(img: Image.Image, cfg: DataConfig):
    w, h = img.size
    scale = cfg.eval_resize / min(w, h)
    rw, rh = int(round(w * scale)), int(round(h * scale))
    img = img.resize((rw, rh), Image.BILINEAR)
    left = (rw - cfg.image_size) // 2
    top = (rh - cfg.image_size) // 2
    img = img.crop((left, top, left + cfg.image_size, top + cfg.image_size))
    arr = np.asarray(img, np.float32) / 255.0
    return (arr - np.asarray(cfg.mean, np.float32)) / np.asarray(cfg.std, np.float32)


@pytest.mark.parametrize("w,h", [(320, 240), (240, 320), (500, 375), (224, 224)])
def test_eval_transform_matches_pil_reference(w, h):
    cfg = DataConfig(image_size=224, eval_resize=256)
    jpeg, img = _make_jpeg(w, h)
    out = data_lib._decode_center_crop(tf, tf.constant(jpeg), cfg)
    out = data_lib._normalize(tf, out, cfg).numpy()
    ref = _pil_reference(img, cfg)
    assert out.shape == ref.shape == (224, 224, 3)
    # un-normalize for an interpretable pixel-value tolerance
    std = np.asarray(cfg.std, np.float32)
    diff_px = np.abs(out - ref) * std * 255.0
    assert np.mean(diff_px) < 2.0, np.mean(diff_px)   # avg within 2/255
    assert np.percentile(diff_px, 99) < 8.0, np.percentile(diff_px, 99)


def test_train_transform_statistics():
    """Random-resized-crop output is in normalized range and actually varies
    crop windows across samples (area/ratio knobs respected in aggregate)."""
    cfg = DataConfig(image_size=64, rrc_area_min=0.25)
    jpeg, _ = _make_jpeg(128, 128)
    outs = []
    for i in range(8):
        # stateless crop: the per-sample key is what varies the windows
        seed2 = tf.constant([0, i], tf.int64)
        img = data_lib._decode_and_random_crop(tf, tf.constant(jpeg), cfg, seed2)
        outs.append(data_lib._normalize(tf, img, cfg).numpy())
    outs = np.stack(outs)
    assert outs.shape == (8, 64, 64, 3)
    assert np.isfinite(outs).all()
    # different random crops -> different images
    assert np.std(outs.mean(axis=(1, 2, 3))) > 1e-3
