"""Per-rule fixture tests for yamt-lint (analysis/).

Every rule is proven twice: a bad fixture that MUST flag (and flag only that
rule) and a clean fixture that MUST stay silent — so a rule that silently
stops firing (or starts over-firing) breaks the gate, not just the linter's
usefulness. Plus framework coverage: suppression comments, reporters, CLI
exit codes, syntax-error handling.
"""

import json
import pathlib

import pytest

from yet_another_mobilenet_series_tpu import analysis
from yet_another_mobilenet_series_tpu.analysis import cli

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "lint"
RULE_IDS = [f"YAMT{i:03d}" for i in range(1, 26)]


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_bad_fixture_flags(rule_id):
    findings = analysis.run_lint([FIXTURES / rule_id.lower() / "bad"])
    assert findings, f"{rule_id}: bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule_id}, (
        f"{rule_id}: bad fixture flagged other rules too: "
        + "\n".join(f.format() for f in findings)
    )


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_silent(rule_id):
    findings = analysis.run_lint([FIXTURES / rule_id.lower() / "clean"])
    assert findings == [], (
        f"{rule_id}: clean fixture must not flag:\n" + "\n".join(f.format() for f in findings)
    )


# -- suppressions -----------------------------------------------------------


def test_line_suppression(tmp_path):
    (tmp_path / "m.py").write_text("from jax import shard_map  # yamt-lint: disable=YAMT006\n")
    assert analysis.run_lint([tmp_path]) == []


def test_line_suppression_is_rule_scoped(tmp_path):
    # suppressing a DIFFERENT rule must not silence this one
    (tmp_path / "m.py").write_text("from jax import shard_map  # yamt-lint: disable=YAMT001\n")
    assert [f.rule for f in analysis.run_lint([tmp_path])] == ["YAMT006"]


def test_file_suppression(tmp_path):
    (tmp_path / "m.py").write_text(
        "# yamt-lint: disable-file=YAMT006\n"
        "from jax import shard_map\n"
        "from jax.experimental import maps\n"
    )
    assert analysis.run_lint([tmp_path]) == []


def test_disable_all(tmp_path):
    (tmp_path / "m.py").write_text("from jax import shard_map  # yamt-lint: disable=all\n")
    assert analysis.run_lint([tmp_path]) == []


def test_suppression_in_docstring_is_not_a_suppression(tmp_path):
    # suppression syntax QUOTED in a docstring (e.g. core.py's own usage
    # examples) must not register: only real COMMENT tokens count
    (tmp_path / "m.py").write_text(
        '"""Example:  # yamt-lint: disable-file=YAMT006\n'
        'and inline:  # yamt-lint: disable=YAMT006\n'
        '"""\n'
        "from jax import shard_map\n"
    )
    assert [f.rule for f in analysis.run_lint([tmp_path])] == ["YAMT006"]


# -- stale-suppression audit ------------------------------------------------


def test_stale_suppression_flagged(tmp_path):
    (tmp_path / "m.py").write_text(
        "import jax  # yamt-lint: disable=YAMT006 — stale: plain jax import is fine\n"
    )
    findings = analysis.check_suppressions([tmp_path])
    assert [(f.rule, f.line) for f in findings] == [("YAMT900", 1)]


def test_live_suppression_not_flagged(tmp_path):
    (tmp_path / "m.py").write_text("from jax import shard_map  # yamt-lint: disable=YAMT006\n")
    assert analysis.check_suppressions([tmp_path]) == []
    assert analysis.run_lint([tmp_path]) == []


def test_stale_file_suppression_flagged(tmp_path):
    (tmp_path / "m.py").write_text(
        "# yamt-lint: disable-file=YAMT006\n"
        "import jax\n"
    )
    findings = analysis.check_suppressions([tmp_path])
    assert [(f.rule, f.line) for f in findings] == [("YAMT900", 1)]
    assert "file-wide" in findings[0].message


def test_suppression_audit_respects_select(tmp_path):
    # rules outside the selection are not re-run, so their suppressions are
    # left alone rather than declared stale
    (tmp_path / "m.py").write_text(
        "import jax  # yamt-lint: disable=YAMT006\n"
    )
    assert analysis.check_suppressions([tmp_path], select={"YAMT002"}) == []
    assert analysis.check_suppressions([tmp_path], select={"YAMT006"}) != []


def test_cli_check_suppressions(capsys):
    rc = cli.main([str(FIXTURES / "yamt006" / "clean"), "--check-suppressions"])
    capsys.readouterr()
    assert rc == 0


# -- framework --------------------------------------------------------------


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "m.py").write_text("def broken(:\n")
    findings = analysis.run_lint([tmp_path])
    assert [f.rule for f in findings] == ["YAMT000"]


def test_select_restricts_rules():
    bad = FIXTURES / "yamt001" / "bad"
    assert analysis.run_lint([bad], select={"YAMT006"}) == []
    assert {f.rule for f in analysis.run_lint([bad], select={"YAMT001"})} == {"YAMT001"}


def test_registry_has_all_rules():
    ids = [r.id for r in analysis.load_rules()]
    assert ids == sorted(ids)
    for rid in RULE_IDS:
        assert rid in ids


def test_reporters():
    findings = analysis.run_lint([FIXTURES / "yamt006" / "bad"])
    text = analysis.render_text(findings)
    assert "YAMT006" in text and text.endswith(f"{len(findings)} findings")
    doc = json.loads(analysis.render_json(findings))
    assert doc["count"] == len(doc["findings"]) == len(findings)
    assert {"path", "line", "col", "rule", "message"} <= set(doc["findings"][0])


def test_github_reporter():
    findings = analysis.run_lint([FIXTURES / "yamt006" / "bad"])
    gh = analysis.render_github(findings)
    first = findings[0]
    lines = gh.splitlines()
    assert lines[0].startswith(
        f"::error file={first.path},line={first.line},col={first.col + 1},title={first.rule}::"
    )
    assert sum(ln.startswith("::error ") for ln in lines) == len(findings)
    assert analysis.render_github([]) == "clean: no findings"


def test_cli_github_format(capsys):
    rc = cli.main([str(FIXTURES / "yamt006" / "bad"), "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 1 and out.startswith("::error file=")


# -- CLI --------------------------------------------------------------------


def test_cli_exit_codes_and_json(capsys):
    rc = cli.main([str(FIXTURES / "yamt006" / "bad"), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["count"] >= 1

    rc = cli.main([str(FIXTURES / "yamt006" / "clean"), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["count"] == 0


def test_cli_select_filters(capsys):
    rc = cli.main([str(FIXTURES / "yamt001" / "bad"), "--select", "YAMT006"])
    capsys.readouterr()
    assert rc == 0


def test_cli_list_rules(capsys):
    rc = cli.main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rid in RULE_IDS:
        assert rid in out
