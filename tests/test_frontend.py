"""The HTTP front door (serve/frontend.py + serve/admission.py, ROADMAP
item 1): priority + deadline headers propagate end-to-end, every failure
mode maps to a typed HTTP status, /healthz reflects breaker + queue state,
and `cli/serve.py --listen` survives real traffic and drains on SIGTERM
within serve.drain_timeout_s.

Most tests drive the real HTTP server over loopback against a pure-host
engine double (fast); the one subprocess test exercises the full
train-less path — bundle -> engine -> batcher -> admission -> HTTP -> drain
— with a real compiled engine and a real SIGTERM.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine
from yet_another_mobilenet_series_tpu.serve.frontend import Frontend
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _row_id_predict(images):
    return images[:, 0, 0, :1]


class _EchoEngine:
    def __init__(self, block=None):
        self.block = block

    def predict_async(self, images):
        block = self.block

        class _Handle:
            def result(_self):
                if block is not None:
                    assert block.wait(10)
                return _row_id_predict(images)

        return _Handle()

    def predict(self, images):
        return self.predict_async(images).result()


def _stack(engine=None, *, max_retries=2, breaker_threshold=5, breaker_cooldown_s=0.2,
           weights=(8.0, 3.0, 1.0), queue_depth=64, max_batch=8, reject_unmeetable=True):
    b = PipelinedBatcher(
        engine or _EchoEngine(), max_batch=max_batch, max_wait_ms=1.0,
        queue_depth=queue_depth, drain_timeout_s=2.0,
    ).start()
    ac = AdmissionController(
        b, weights=weights, max_retries=max_retries, retry_backoff_ms=1.0,
        breaker_threshold=breaker_threshold, breaker_cooldown_s=breaker_cooldown_s,
        reject_unmeetable=reject_unmeetable,
    )
    fe = Frontend(ac, port=0).start()
    return b, ac, fe


def _request(url, *, data=None, headers=None, method=None):
    """(status, parsed json body, response headers) without raising on 4xx/5xx."""
    req = urllib.request.Request(url, data=data, headers=headers or {}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _post_image(base, val, *, priority=None, deadline_ms=None):
    headers = {"Content-Type": "application/json"}
    if priority:
        headers["X-Priority"] = priority
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    img = np.full((4, 4, 3), float(val), np.float32).tolist()
    return _request(base + "/predict", data=json.dumps({"image": img}).encode(), headers=headers)


# ---------------------------------------------------------------------------
# request/response semantics
# ---------------------------------------------------------------------------


def test_retry_after_on_overload_verdicts_and_typed_on_client():
    """Every overload-shaped 429/503 carries Retry-After (quota 429s,
    brownout 503s with the shed's OWN bound), the shared client surfaces it
    typed (ClientHTTPError.retry_after — the router's backpressure
    discriminator), and non-overload errors carry no header."""
    from yet_another_mobilenet_series_tpu.serve.brownout import build_ladder
    from yet_another_mobilenet_series_tpu.serve.client import ClientHTTPError, ReplicaClient

    get_registry().reset()
    blocker = threading.Event()
    b, ac, fe = _stack(_EchoEngine(block=blocker), weights=(98.0, 1.0, 1.0), queue_depth=8)
    client = ReplicaClient("127.0.0.1", fe.port)
    try:
        base = fe.url
        # a quota 429: with the engine blocked, concurrent best_effort
        # submits pile onto a 1-slot quota — overload-shaped -> Retry-After
        results = []
        lock = threading.Lock()

        def push():
            st, body, hdrs = _post_image(base, 1.0, priority="best_effort")
            with lock:
                results.append((st, body.get("error"), hdrs.get("Retry-After")))

        threads = [threading.Thread(target=push, daemon=True) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # let the stragglers hit the saturated quota
        blocker.set()
        for t in threads:
            t.join(timeout=15)
        statuses = list(results)
        quota_hits = [s for s in statuses if s[0] == 429]
        assert quota_hits, statuses
        assert all(ra is not None and float(ra) >= 0 for _, _, ra in quota_hits)
        # brownout shed: 503 + the policy's own Retry-After, typed on the client
        ac.apply_brownout(build_ladder(retry_after_s=7.0)[3])
        st, body, hdrs = _post_image(base, 1.0, priority="best_effort")
        assert st == 503 and body["error"] == "brownout"
        assert float(hdrs["Retry-After"]) == 7.0
        with pytest.raises(ClientHTTPError) as ei:
            client.predict(np.zeros((4, 4, 3), np.float32), priority="best_effort")
        assert ei.value.status == 503 and ei.value.tag == "brownout"
        assert ei.value.retry_after == 7.0
        ac.apply_brownout(build_ladder()[0])
        # a 400 (non-overload) carries no Retry-After
        st, _, hdrs = _request(base + "/predict", data=b"{}",
                               headers={"Content-Type": "application/json"})
        assert st == 400 and "Retry-After" not in hdrs
    finally:
        client.close()
        fe.stop()
        b.stop()


def test_healthz_reports_brownout_level():
    from yet_another_mobilenet_series_tpu.serve.brownout import build_ladder

    get_registry().reset()
    b, ac, fe = _stack(_EchoEngine())
    try:
        st, body, _ = _request(fe.url + "/healthz")
        assert st == 200 and body["brownout_level"] == 0
        assert body["brownout"]["level"] == 0
        get_registry().gauge("serve.brownout_level").set(4)
        ac.apply_brownout(build_ladder()[4])
        st, body, _ = _request(fe.url + "/healthz")
        assert st == 200  # degraded, not down: the breaker still gates 503
        assert body["brownout_level"] == 4
        assert body["brownout"]["shed_classes"] == ["batch", "best_effort"]
        assert body["brownout"]["retries_enabled"] is True
    finally:
        fe.stop()
        b.stop()


def test_predict_json_round_trip_with_priority_and_deadline():
    b, ac, fe = _stack()
    try:
        status, doc, _ = _post_image(fe.url, 7, priority="batch", deadline_ms=5000)
        assert status == 200
        assert doc["priority"] == "batch"
        assert doc["logits"] == [7.0]
        snap = get_registry().snapshot()
        assert snap["serve.requests.batch"] >= 1  # the header reached admission
        assert snap["serve.latency_seconds.batch.count"] >= 1
    finally:
        fe.stop()
        b.stop()


def test_predict_raw_tensor_body():
    b, ac, fe = _stack()
    try:
        img = np.full((4, 4, 3), 5.0, np.float32)
        status, doc, _ = _request(
            fe.url + "/predict", data=img.tobytes(),
            headers={"Content-Type": "application/octet-stream", "X-Shape": "4,4,3"},
        )
        assert status == 200 and doc["logits"] == [5.0]
        # shape mismatch is a 400, not a crash
        status, doc, _ = _request(
            fe.url + "/predict", data=img.tobytes(),
            headers={"Content-Type": "application/octet-stream", "X-Shape": "8,8,3"},
        )
        assert status == 400 and doc["error"] == "bad_request"
    finally:
        fe.stop()
        b.stop()


def test_predict_u8_wire_via_x_dtype_header():
    """X-Dtype: u8 carries RAW uint8 pixels end-to-end — the quantized
    wire's 4x byte drop crossing the HTTP edge intact (a u8 body is a
    quarter the bytes of the same image as f4) — and the typed client
    sends it automatically for uint8 arrays. Unknown codes are a 400."""
    from yet_another_mobilenet_series_tpu.serve.client import ReplicaClient

    b, ac, fe = _stack()
    try:
        img_u8 = np.full((4, 4, 3), 200, np.uint8)
        body = img_u8.tobytes()
        assert len(body) == 4 * 4 * 3  # a quarter of the f4 wire's 192
        status, doc, _ = _request(
            fe.url + "/predict", data=body,
            headers={"Content-Type": "application/octet-stream",
                     "X-Shape": "4,4,3", "X-Dtype": "u8"},
        )
        assert status == 200 and doc["logits"] == [200.0]
        # the shared client picks the code from the array dtype
        client = ReplicaClient("127.0.0.1", fe.port, timeout_s=10.0)
        assert client.predict(img_u8).tolist() == [200.0]
        client.close()
        # absent header = the f4 contract (pre-header clients keep working)
        f4 = np.full((4, 4, 3), 7.0, np.float32)
        status, doc, _ = _request(
            fe.url + "/predict", data=f4.tobytes(),
            headers={"Content-Type": "application/octet-stream", "X-Shape": "4,4,3"},
        )
        assert status == 200 and doc["logits"] == [7.0]
        # unknown dtype codes and a u8-sized body declared f4 are 400s
        status, doc, _ = _request(
            fe.url + "/predict", data=body,
            headers={"Content-Type": "application/octet-stream",
                     "X-Shape": "4,4,3", "X-Dtype": "f2"},
        )
        assert status == 400 and "X-Dtype" in doc["message"]
        status, doc, _ = _request(
            fe.url + "/predict", data=body,
            headers={"Content-Type": "application/octet-stream", "X-Shape": "4,4,3"},
        )
        assert status == 400 and doc["error"] == "bad_request"
    finally:
        fe.stop()
        b.stop()


def test_membership_endpoints_register_deregister():
    """POST /register|/deregister serve the TTL-lease protocol when the
    admission object speaks it (the fleet Router); a plain replica answers
    404 so a misconfigured heartbeat is loud."""
    from yet_another_mobilenet_series_tpu.serve.client import ClientHTTPError, ReplicaClient

    # a plain replica: 404
    b, ac, fe = _stack()
    try:
        client = ReplicaClient("127.0.0.1", fe.port, timeout_s=10.0)
        with pytest.raises(ClientHTTPError) as ei:
            client.register("127.0.0.1", 9999, ttl_s=5.0)
        assert ei.value.status == 404
        client.close()
    finally:
        fe.stop()
        b.stop()

    # a router-shaped admission: the lease round-trips over the wire
    class _FakeRouterAdmission:
        def __init__(self):
            self.calls = []

        def submit(self, image, **kw):
            raise AssertionError("not exercised here")

        def state(self):
            return {"breaker_state": 0, "queued_total": 0}

        def register(self, host, port, *, ttl_s=None, replica_id=""):
            if ttl_s is not None and ttl_s <= 0:
                raise ValueError("lease ttl_s must be > 0")
            self.calls.append(("register", host, port, ttl_s, replica_id))
            return {"ok": True, "key": f"{host}:{port}", "ttl_s": ttl_s or 5.0,
                    "new": True, "source": "lease", "replica_id": replica_id}

        def deregister(self, host, port):
            self.calls.append(("deregister", host, port))
            return {"ok": True, "key": f"{host}:{port}"}

    fake = _FakeRouterAdmission()
    fe2 = Frontend(fake, port=0, replica_id="router").start()
    try:
        client = ReplicaClient("127.0.0.1", fe2.port, timeout_s=10.0)
        doc = client.register("127.0.0.1", 9001, ttl_s=2.5, replica_id="r-x")
        assert doc["ok"] and doc["ttl_s"] == 2.5
        doc = client.deregister("127.0.0.1", 9001)
        assert doc["ok"]
        assert fake.calls == [("register", "127.0.0.1", 9001, 2.5, "r-x"),
                              ("deregister", "127.0.0.1", 9001)]
        # malformed bodies and rejected leases map to 400
        status, doc, _ = _request(fe2.url + "/register", data=b"not json",
                                  headers={"Content-Type": "application/json"})
        assert status == 400 and doc["error"] == "bad_request"
        status, doc, _ = _request(
            fe2.url + "/register",
            data=json.dumps({"host": "127.0.0.1", "port": 9001, "ttl_s": -1}).encode(),
            headers={"Content-Type": "application/json"})
        assert status == 400 and "ttl_s" in doc["message"]
        client.close()
    finally:
        fe2.stop()


def test_malformed_requests_get_400_and_404():
    b, ac, fe = _stack()
    try:
        for payload in [b"not json", json.dumps({"not_image": 1}).encode(),
                        json.dumps({"image": [1.0, 2.0]}).encode()]:
            status, doc, _ = _request(fe.url + "/predict", data=payload,
                                      headers={"Content-Type": "application/json"})
            assert status == 400 and doc["error"] == "bad_request"
        status, doc, _ = _post_image(fe.url, 1, priority="platinum")
        assert status == 400 and "platinum" in doc["message"]
        assert _request(fe.url + "/nope", data=b"x")[0] == 404
        assert _request(fe.url + "/nope")[0] == 404
    finally:
        fe.stop()
        b.stop()


def test_deadline_shed_maps_to_504():
    gate = threading.Event()
    b = PipelinedBatcher(_EchoEngine(block=gate), max_batch=1, max_inflight=1,
                         max_wait_ms=0.0, queue_depth=64, drain_timeout_s=5.0).start()
    ac = AdmissionController(b, max_retries=2, retry_backoff_ms=1.0, reject_unmeetable=False)
    fe = Frontend(ac, port=0).start()
    try:
        # request 0 wedges the single in-flight slot; request 1's deadline
        # expires while it waits behind it -> shed -> 504
        responses = {}

        def post(i, deadline_ms):
            responses[i] = _post_image(fe.url, i, deadline_ms=deadline_ms)

        slow = threading.Thread(target=post, args=(0, 30000), daemon=True)
        doomed = threading.Thread(target=post, args=(1, 40.0), daemon=True)
        slow.start()
        time.sleep(0.1)
        doomed.start()
        time.sleep(0.2)  # deadline 1 expires while the window is wedged
        gate.set()
        slow.join(timeout=30)
        doomed.join(timeout=30)
        assert responses[0][0] == 200
        status, doc, _ = responses[1]
        assert status == 504 and doc["error"] == "deadline_exceeded"
    finally:
        gate.set()
        fe.stop()
        b.stop()


def test_breaker_drill_over_http_and_healthz():
    """Engine errors surface as 500s, the streak opens the breaker (503 +
    Retry-After, healthz flips to 503/open), the cooldown probe closes it
    (healthz back to 200/closed)."""
    eng = FaultyEngine(_EchoEngine(), fail_first_n=3)
    b, ac, fe = _stack(eng, max_retries=0, breaker_threshold=3, breaker_cooldown_s=0.3)
    try:
        status, doc, _ = _request(fe.url + "/healthz")
        assert status == 200 and doc["ok"] and doc["breaker"] == "closed"
        assert set(doc["classes"]) == {"interactive", "batch", "best_effort"}
        for _ in range(3):
            status, doc, _ = _post_image(fe.url, 1)
            assert status == 500 and doc["error"] == "engine_error"
        status, doc, headers = _post_image(fe.url, 1)
        assert status == 503 and doc["error"] == "breaker_open"
        assert float(headers["Retry-After"]) >= 0
        status, doc, _ = _request(fe.url + "/healthz")
        assert status == 503 and doc["breaker"] == "open" and not doc["ok"]
        time.sleep(0.35)  # cooldown -> the next predict is the half-open probe
        status, doc, _ = _post_image(fe.url, 6)
        assert status == 200 and doc["logits"] == [6.0]
        status, doc, _ = _request(fe.url + "/healthz")
        assert status == 200 and doc["breaker"] == "closed"
    finally:
        fe.stop()
        b.stop()


def test_class_quota_rejections_map_to_429():
    """best_effort floods 429 at their weighted share while interactive
    still admits — the QoS point of per-class admission."""
    gate = threading.Event()
    b, ac, fe = _stack(_EchoEngine(block=gate), weights=(8.0, 3.0, 1.0),
                       queue_depth=12, max_batch=1)
    try:
        results = {"ok_or_pending": 0, "rejected": 0}
        lock = threading.Lock()

        def flood(i):
            status, doc, _ = _post_image(fe.url, i, priority="best_effort", deadline_ms=30000)
            with lock:
                if status == 429:
                    assert doc["error"] == "queue_full"
                    results["rejected"] += 1
                else:
                    results["ok_or_pending"] += 1

        threads = [threading.Thread(target=flood, args=(i,), daemon=True) for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # floods are queued/rejected; engine still wedged
        # interactive has its own share: admitted despite the flood
        status_doc = {}

        def interactive():
            status_doc["r"] = _post_image(fe.url, 9, priority="interactive", deadline_ms=30000)

        it = threading.Thread(target=interactive, daemon=True)
        it.start()
        time.sleep(0.2)
        gate.set()
        it.join(timeout=30)
        for t in threads:
            t.join(timeout=30)
        status, doc, _ = status_doc["r"]
        assert status == 200 and doc["logits"] == [9.0]
        assert results["rejected"] >= 1  # the flood hit its quota
    finally:
        gate.set()
        fe.stop()
        b.stop()


def test_reject_unmeetable_deadline_at_arrival():
    """Once the latency EWMA knows the service is slow, a request whose
    deadline cannot be met is rejected at ARRIVAL (429 deadline_unmeetable),
    before burning a queue slot."""
    class _Slow(_EchoEngine):
        def predict_async(self, images):
            time.sleep(0.05)
            return super().predict_async(images)

    b, ac, fe = _stack(_Slow(), max_batch=1)
    try:
        assert _post_image(fe.url, 1)[0] == 200  # teaches the EWMA ~50ms
        assert ac.predicted_wait_s() > 0.01
        status, doc, _ = _post_image(fe.url, 2, deadline_ms=1.0)
        assert status == 429 and doc["error"] == "deadline_unmeetable"
        assert get_registry().snapshot()["serve.rejected_deadline"] >= 1
        # a meetable deadline still admits
        assert _post_image(fe.url, 3, deadline_ms=30000)[0] == 200
    finally:
        fe.stop()
        b.stop()


def test_concurrent_http_clients_route_rows():
    b, ac, fe = _stack()
    try:
        results = {}
        lock = threading.Lock()

        def client(i):
            status, doc, _ = _post_image(fe.url, i, priority=("interactive", "batch")[i % 2])
            with lock:
                results[i] = (status, doc["logits"])

        threads = [threading.Thread(target=client, args=(i,), daemon=True) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert results == {i: (200, [float(i)]) for i in range(16)}
    finally:
        fe.stop()
        b.stop()


# ---------------------------------------------------------------------------
# per-request telemetry: X-Request-Id, /metrics, /varz, trace correlation
# ---------------------------------------------------------------------------


def test_request_id_round_trip():
    """Every /predict response carries X-Request-Id: minted monotonic ids by
    default, a client-supplied id echoed back verbatim, and the header rides
    error responses too (a 429/5xx is exactly when you want the id)."""
    b, ac, fe = _stack()
    try:
        _, _, h1 = _post_image(fe.url, 1)
        _, _, h2 = _post_image(fe.url, 2)
        rid1, rid2 = int(h1["X-Request-Id"]), int(h2["X-Request-Id"])
        assert rid2 > rid1 > 0  # minted, process-monotonic
        # the body carries it too (clients that drop headers still get it)
        status, doc, h3 = _post_image(fe.url, 3)
        assert doc["request_id"] == h3["X-Request-Id"]
        # client-supplied correlation id is echoed verbatim
        img = np.full((4, 4, 3), 4.0, np.float32).tolist()
        status, doc, hdrs = _request(
            fe.url + "/predict", data=json.dumps({"image": img}).encode(),
            headers={"Content-Type": "application/json", "X-Request-Id": "client-abc-7"},
        )
        assert status == 200 and hdrs["X-Request-Id"] == "client-abc-7"
        assert doc["request_id"] == "client-abc-7"
        # errors carry the id as well (unknown class -> 400)
        status, doc, hdrs = _post_image(fe.url, 5, priority="platinum")
        assert status == 400 and hdrs.get("X-Request-Id")
    finally:
        fe.stop()
        b.stop()


def test_metrics_and_varz_scrape_surface():
    """GET /metrics returns Prometheus text exposition with per-class
    latency bucket + quantile lines; GET /varz the JSON registry snapshot
    (quantile columns included) plus admission state."""
    b, ac, fe = _stack()
    try:
        assert _post_image(fe.url, 1, priority="batch")[0] == 200
        req = urllib.request.Request(fe.url + "/metrics")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "# TYPE serve_latency_seconds histogram" in text
        assert 'serve_latency_seconds_bucket{class="batch",le="+Inf"}' in text
        assert 'serve_latency_seconds{class="batch",quantile="0.99"}' in text
        assert 'serve_requests{class="batch"}' in text
        status, varz, _ = _request(fe.url + "/varz")
        assert status == 200
        assert varz["metrics"]["serve.latency_seconds.batch.count"] >= 1
        assert "serve.latency_seconds.batch.p99" in varz["metrics"]
        assert varz["metrics"]["serve.latency_seconds.batch.min"] > 0
        assert varz["admission"]["breaker"] == "closed"
        # device-telemetry surfaces ride /varz too: build identity + the
        # per-executable compile/cost table (dict; empty for this host-double
        # engine, populated by any real warmed engine in this process)
        assert isinstance(varz["build_info"], dict)
        assert isinstance(varz["executables"], dict)
    finally:
        fe.stop()
        b.stop()


def test_metrics_build_info_family():
    """/metrics carries the build_info version-attribution family once the
    CLI stamps it (cli/serve.py run() does at startup)."""
    from yet_another_mobilenet_series_tpu.obs import device as obs_device

    get_registry().set_build_info(obs_device.build_info())
    b, ac, fe = _stack()
    try:
        with urllib.request.urlopen(fe.url + "/metrics", timeout=30) as r:
            text = r.read().decode()
        line = next(l for l in text.splitlines() if l.startswith("build_info{"))
        assert "git_sha=" in line and "jax_version=" in line and "platform=" in line
        assert line.endswith("} 1")
    finally:
        fe.stop()
        b.stop()


def test_profiler_capture_endpoints(tmp_path):
    """POST /profile/start|stop: 200 with the trace dir, 409 on double
    start/stop, xplane dump on disk for trace_ops, 404 when unconfigured."""
    from yet_another_mobilenet_series_tpu.obs import device as obs_device

    b, ac, _fe = _stack()
    _fe.stop()  # rebuild with a profiler attached (same admission stack)
    cap = obs_device.ProfilerCapture(str(tmp_path / "trace"))
    fe = Frontend(ac, port=0, profiler=cap).start()
    try:
        status, body, _ = _request(fe.url + "/profile/start", data=b"", method="POST")
        assert status == 200 and body["ok"] and body["trace_dir"].endswith("trace")
        status, body, _ = _request(fe.url + "/profile/start", data=b"", method="POST")
        assert status == 409 and body["error"] == "profiler_state"
        # capture real serving traffic inside the window
        assert _post_image(fe.url, 3)[0] == 200
        status, body, _ = _request(fe.url + "/profile/stop", data=b"", method="POST")
        assert status == 200 and body["captured_s"] >= 0
        assert list((tmp_path / "trace").rglob("*.xplane.pb"))
        status, body, _ = _request(fe.url + "/profile/stop", data=b"", method="POST")
        assert status == 409
    finally:
        fe.stop()
        b.stop()
    # no profiler configured -> 404, never a crash
    b2, ac2, fe2 = _stack()
    try:
        status, body, _ = _request(fe2.url + "/profile/start", data=b"", method="POST")
        assert status == 404
    finally:
        fe2.stop()
        b2.stop()


def test_quantile_deadline_predictor():
    """predictor="quantile": once the class histogram has data, the wait
    prediction is the configured latency quantile (tail-aware) and feeds
    reject-on-arrival exactly like the EWMA mode."""
    class _Slow(_EchoEngine):
        def predict_async(self, images):
            time.sleep(0.05)
            return super().predict_async(images)

    get_registry().reset()  # the class histogram must start empty here
    b = PipelinedBatcher(_Slow(), max_batch=1, max_wait_ms=1.0,
                         queue_depth=64, drain_timeout_s=2.0).start()
    ac = AdmissionController(b, predictor="quantile", predictor_quantile=0.95)
    try:
        assert ac.predicted_wait_s("interactive") == 0.0  # no data yet
        fut = ac.submit(np.zeros((4, 4, 3), np.float32))
        fut.result(timeout=30)
        wait = ac.predicted_wait_s("interactive")
        assert wait > 0.01  # learned the ~50 ms tail from the histogram
        from yet_another_mobilenet_series_tpu.serve.admission import DeadlineUnmeetable
        with pytest.raises(DeadlineUnmeetable):
            ac.submit(np.zeros((4, 4, 3), np.float32), deadline_ms=1.0)
        assert ac.state()["predictor"] == "quantile"
    finally:
        b.stop()
    with pytest.raises(ValueError, match="predictor"):
        AdmissionController(b, predictor="p99ish")


def test_trace_correlates_one_request_across_threads():
    """The tentpole invariant, in-process: one request id appears in async
    (b/e) AND flow (s/t/f) events emitted from at least two distinct
    threads — handler, collect, completion — so Perfetto renders the
    request as one correlated waterfall."""
    from yet_another_mobilenet_series_tpu.obs import trace as obs_trace

    prev = obs_trace.get_tracer()
    tr = obs_trace.configure(enabled=True, ring_size=4096)
    try:
        b, ac, fe = _stack()
        try:
            status, _, hdrs = _post_image(fe.url, 3)
            assert status == 200
            rid = int(hdrs["X-Request-Id"])
        finally:
            fe.stop()
            b.stop()
        evts = [e for e in tr.to_chrome_trace()["traceEvents"] if e.get("id") == rid]
        phases = {e["ph"] for e in evts}
        assert {"b", "e"} <= phases, phases  # async waterfall edges
        assert {"s", "f"} <= phases, phases  # flow arrows
        assert len({e["tid"] for e in evts}) >= 2  # across threads
        names = {e["name"] for e in evts}
        assert {"serve/request", "serve/queued", "serve/inflight", "serve/req"} <= names
        # the envelope records the outcome
        env_end = next(e for e in evts if e["ph"] == "e" and e["name"] == "serve/request")
        assert env_end["args"]["outcome"] == "completed"
    finally:
        obs_trace._TRACER = prev


# ---------------------------------------------------------------------------
# the full front door: cli/serve.py --listen + SIGTERM drain (subprocess)
# ---------------------------------------------------------------------------

_LISTEN_DRIVER = """
import os, sys
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import jax
jax.config.update("jax_platforms", "cpu")
from yet_another_mobilenet_series_tpu.cli.serve import main
main(sys.argv[1:])
"""


def test_cli_listen_end_to_end_sigterm_drain(tmp_path):
    """cli/serve.py --listen against a real exported bundle: HTTP predict
    with priority + deadline headers, /healthz with breaker/queue state,
    then SIGTERM -> graceful drain within serve.drain_timeout_s."""
    import jax

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.serve.export import export_bundle

    net = get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=4, dropout=0.0,
                    block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}]),
        image_size=24,
    )
    params, state = net.init(jax.random.PRNGKey(0))
    bundle_dir = str(tmp_path / "bundle")
    export_bundle(net, params, state, bundle_dir)

    log_dir = str(tmp_path / "srv")
    proc = subprocess.Popen(
        [sys.executable, "-c", _LISTEN_DRIVER, "--listen",
         f"serve.bundle={bundle_dir}", "serve.buckets=[1,4]", "data.image_size=24",
         "serve.drain_timeout_s=10", "obs.trace=true", f"train.log_dir={log_dir}"],
        env=dict(os.environ, PYTHONPATH=REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        addr_path = os.path.join(log_dir, "listen_addr.json")
        deadline = time.time() + 120
        while not os.path.exists(addr_path):
            assert proc.poll() is None, f"server died early:\n{proc.stdout.read()[-2000:]}"
            assert time.time() < deadline, "server never bound"
            time.sleep(0.2)
        addr = json.loads(open(addr_path).read())
        base = f"http://{addr['host']}:{addr['port']}"

        status, doc, hdrs = _post_image(base, 2, priority="interactive", deadline_ms=30000)
        assert status == 200 and len(doc["logits"]) == 4
        request_id = int(hdrs["X-Request-Id"])
        status, health, _ = _request(base + "/healthz")
        assert status == 200 and health["breaker"] == "closed"
        assert health["classes"]["interactive"]["quota"] >= 1
        # the live scrape surface: Prometheus exposition with per-class
        # latency bucket + quantile lines (the acceptance criterion)
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            metrics_text = r.read().decode()
        assert 'serve_latency_seconds_bucket{class="interactive",le="+Inf"}' in metrics_text
        assert 'serve_latency_seconds{class="interactive",quantile="0.99"}' in metrics_text
        status, varz, _ = _request(base + "/varz")
        assert status == 200 and varz["metrics"]["serve.latency_seconds.interactive.count"] >= 1

        proc.send_signal(signal.SIGTERM)
        t0 = time.time()
        rc = proc.wait(timeout=30)
        assert rc == 0
        assert time.time() - t0 < 15  # drained inside the configured bound
        out = proc.stdout.read()
        assert "drained in" in out and "clean" in out
        # obs artifacts landed, with the front-door counters in them
        snap = json.loads(open(os.path.join(log_dir, "obs_registry.json")).read())
        assert snap["serve.requests.interactive"] >= 1
        assert snap["serve.http_requests"] >= 1
        assert snap["serve.breaker_state"] == 0
        assert snap["serve.latency_seconds.interactive.p99"] > 0
        # the trace correlates the served request's id across threads:
        # async (b/e) waterfall edges AND flow (s/t/f) arrows from at least
        # two distinct tids (HTTP handler / collect / completion)
        trace = json.loads(open(os.path.join(log_dir, "obs_trace.json")).read())
        corr = [e for e in trace["traceEvents"] if e.get("id") == request_id]
        phases = {e["ph"] for e in corr}
        assert {"b", "e"} <= phases and ({"s", "t", "f"} & phases), phases
        assert len({e["tid"] for e in corr}) >= 2
        assert {"serve/request", "serve/queued", "serve/inflight"} <= {e["name"] for e in corr}
        thread_rows = {e["args"]["name"] for e in trace["traceEvents"]
                       if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"serve-collect", "serve-complete", "serve-http"} <= thread_rows
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
