"""Direct unit tests for the small host-side utilities: meters (the
AverageMeter/accuracy surface of the reference's utils/common.py,
SURVEY.md §2 #13) and the pytree structure mapper shared by ZeRO and NAS
rematerialization. Both were previously covered only through integration."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.utils import treeutil
from yet_another_mobilenet_series_tpu.utils.meters import AverageMeter, MetricLogger, format_metrics


def test_average_meter_weighted_and_reset():
    m = AverageMeter()
    assert m.avg == 0.0  # empty meter must not divide by zero
    m.update(1.0, n=3)
    m.update(5.0, n=1)
    assert m.avg == pytest.approx((1.0 * 3 + 5.0) / 4)
    m.reset()
    assert m.count == 0 and m.sum == 0.0


def test_metric_logger_averages_and_throughput():
    log = MetricLogger()
    # device arrays go in; floats come out only at snapshot (async-dispatch
    # safety is the module's whole point — update() must not call float())
    log.update({"loss": jnp.asarray(2.0), "top1": jnp.asarray(0.25)}, batch_images=64)
    log.update({"loss": jnp.asarray(4.0), "top1": jnp.asarray(0.75)}, batch_images=64)
    time.sleep(0.01)
    out = log.snapshot_and_reset(num_chips=8)
    assert out["loss"] == pytest.approx(3.0)
    assert out["top1"] == pytest.approx(0.5)
    assert out["images_per_sec"] > 0
    assert out["images_per_sec_per_chip"] == pytest.approx(out["images_per_sec"] / 8)
    # reset: a second snapshot has no carried-over state
    out2 = log.snapshot_and_reset()
    assert "loss" not in out2 and "images_per_sec" not in out2


def test_metric_logger_no_images_no_throughput_keys():
    log = MetricLogger()
    log.update({"loss": jnp.asarray(1.0)})
    out = log.snapshot_and_reset()
    assert "images_per_sec" not in out


def test_format_metrics_sorted_and_compact():
    s = format_metrics("eval:", {"b": 2.0, "a": 0.123456})
    assert s == "eval: a=0.1235 b=2"


def test_map_params_shaped_finds_nested_trees():
    """The ZeRO/remat contract: fn applies to every subtree structurally
    equal to the params tree, wherever the optimizer composition nests it —
    and to nothing else."""
    import collections

    params = {"a": jnp.zeros((3,)), "b": {"w": jnp.ones((2, 2))}}
    pstruct = jax.tree.structure(params)
    State = collections.namedtuple("State", ["mu", "nu", "count"])
    opt_state = (
        State(mu=params, nu=jax.tree.map(lambda x: x + 1, params), count=jnp.zeros(())),
        {"inner": params, "scalar": 7},
    )

    tagged = treeutil.map_params_shaped(
        opt_state, pstruct, lambda sub: jax.tree.map(lambda x: x + 100, sub)
    )
    # all three params-shaped subtrees transformed...
    np.testing.assert_array_equal(np.asarray(tagged[0].mu["a"]), 100 * np.ones(3))
    np.testing.assert_array_equal(np.asarray(tagged[0].nu["b"]["w"]), 102 * np.ones((2, 2)))
    np.testing.assert_array_equal(np.asarray(tagged[1]["inner"]["a"]), 100 * np.ones(3))
    # ...NamedTuple type and non-matching leaves preserved
    assert type(tagged[0]).__name__ == "State"
    assert float(tagged[0].count) == 0.0
    assert tagged[1]["scalar"] == 7


def test_map_params_shaped_identity_on_no_match():
    params = {"a": jnp.zeros((3,))}
    other = {"x": 1, "y": (2, 3)}
    out = treeutil.map_params_shaped(other, jax.tree.structure(params), lambda s: "BOOM")
    assert out == other


def test_profile_cli_prints_totals_and_atom_table(capsys):
    """The profiler CLI (reference: model_profiling's printed summary,
    SURVEY.md §2 #10): totals for a plain arch; per-block atom-cost table
    for a supernet (the AtomNAS penalty's weighting data)."""
    from yet_another_mobilenet_series_tpu.cli import profile as cli_profile

    cli_profile.main(["model.arch=mobilenet_v2", "data.image_size=64"])
    out = capsys.readouterr().out
    assert "mobilenet_v2 x1.0" in out
    assert "total:" in out and "M MACs" in out and "M params" in out
    assert "atom cost table" not in out  # single-kernel net: no atoms

    cli_profile.main([
        "model.arch=atomnas_supernet", "data.image_size=64", "model.num_classes=10",
    ])
    out = capsys.readouterr().out
    assert "atom cost table" in out
    assert "atoms=" in out
