#!/usr/bin/env python
"""Measured per-block latency table for latency-aware NAS (ROADMAP item 3).

FLOPs is a poor proxy for measured latency (PAPERS.md: FLASH arXiv
2108.00568, LANA arXiv 2107.10624), so this benches every DISTINCT block
configuration of a network — (in/out channels, expanded width, kernel split,
stride, SE, input resolution) — at several expanded-channel width fractions,
through the same AOT path the serving engine uses
(``jit(...).lower().compile()`` via obs/device.timed_compile, so compile
time and cost_analysis flops/bytes are recorded for every entry too), and
writes a ``LATENCY_TABLE_*.json`` artifact. ``nas/latency.py`` loads it and
turns the (alive channels -> seconds) ladders into per-atom marginal-latency
cost vectors; ``prune.cost="latency_table"`` swaps them into the AtomNAS
penalty — the search then optimizes what the serving fleet actually pays.

Artifact contract: bench.py shape — exactly ONE JSON line on stdout, exit 0
always (structured ``error`` field on failure), optional ``--out`` copy,
provenance-stamped (bench.stamp_provenance: jax/jaxlib versions, platform,
device kind, cpu-rehearsal flag). Entries measured on this 1-core rehearsal
box carry ``cpu_rehearsal: true``; the real table is a TPU/accelerator run
of the same command (ROADMAP item 3's hardware rung).

Usage: python scripts/latency_table.py [--arch mobilenet_v3_large]
           [--image-sizes 224] [--widths 0.375,0.6875,1.0] [--batch 8]
           [--iters 12] [--out LATENCY_TABLE_r01_cpu_rehearsal.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _width_variant(spec, width: float):
    """The block at ``width`` x expanded channels (>= one channel per kernel
    branch), channels re-split across kernel branches the same way the
    supernet builder splits them — the shape a width-pruned block actually
    runs at. SE width stays fixed: masking prunes expanded channels, not the
    SE bottleneck (nas/masking.py semantics)."""
    from yet_another_mobilenet_series_tpu.models.specs import _split_groups

    e = max(len(spec.kernel_sizes), int(round(spec.expanded_channels * width)))
    return dataclasses.replace(
        spec, expanded_channels=e, group_channels=_split_groups(e, spec.kernel_sizes),
        force_expand=True,
    )


def bench_block(spec, image_size: int, widths, batch: int, iters: int) -> dict:
    """One table entry: the block's eval forward AOT-compiled and timed at
    each width. Serve-engine idiom — AOT ``lower().compile()`` through
    obs/device.timed_compile (compile + cost accounting recorded per width),
    one untimed page-in, then ``iters`` timed back-to-back runs off one
    device-resident input (no donation: the timed loop reuses the buffer,
    and a per-iter allocation would pollute the device measurement) with one
    hard sync at the end, so the number is steady-state device latency."""
    import jax
    import jax.numpy as jnp

    from yet_another_mobilenet_series_tpu.nas.latency import block_key
    from yet_another_mobilenet_series_tpu.obs import device as obs_device

    key = block_key(spec, image_size)
    alive, lat, compile_s, flops = [], [], [], []
    for w in sorted(widths):
        blk = _width_variant(spec, w)
        params, state = blk.init(jax.random.PRNGKey(0))

        def run(p, s, x):
            return blk.apply(p, s, x, train=False)[0]

        x_shape = jax.ShapeDtypeStruct((batch, image_size, image_size, spec.in_channels), jnp.float32)
        t0 = time.perf_counter()
        exe = obs_device.timed_compile(
            jax.jit(run).lower(params, state, x_shape),
            f"latbl_{key}_w{blk.expanded_channels}",
        )
        compile_s.append(round(time.perf_counter() - t0, 4))
        x = jnp.zeros((batch, image_size, image_size, spec.in_channels), jnp.float32)
        exe(params, state, x).block_until_ready()  # untimed page-in
        t0 = time.perf_counter()
        for _ in range(iters):
            y = exe(params, state, x)
        y.block_until_ready()
        lat.append((time.perf_counter() - t0) / (iters * batch))  # s / image
        alive.append(blk.expanded_channels)
        flops.append(obs_device.flops_for(f"latbl_{key}_w{blk.expanded_channels}"))
    return {
        "key": key,
        "in_channels": spec.in_channels,
        "out_channels": spec.out_channels,
        "expanded_channels": spec.expanded_channels,
        "kernel_sizes": list(spec.kernel_sizes),
        "stride": spec.stride,
        "se_channels": spec.se_channels,
        "image_size": image_size,
        "alive_channels": alive,
        "latency_s": [round(v, 9) for v in lat],
        "cost_flops": flops,
        "compile_s": compile_s,
    }


def build_table(net, image_sizes, widths, batch: int, iters: int,
                log=lambda msg: None) -> list[dict]:
    """Every DISTINCT block signature of ``net`` x every image size, deduped
    by table key (repeated stages share one measurement)."""
    from yet_another_mobilenet_series_tpu.nas.latency import block_input_sizes, block_key

    entries: dict[str, dict] = {}
    for image_size in image_sizes:
        sizes = block_input_sizes(net, image_size)
        for i, blk in enumerate(net.blocks):
            key = block_key(blk, sizes[i])
            if key in entries:
                continue
            t0 = time.perf_counter()
            entries[key] = bench_block(blk, sizes[i], widths, batch, iters)
            log(f"[{len(entries)}] {key}: "
                f"{[round(v * 1e6, 1) for v in entries[key]['latency_s']]} µs/img "
                f"({time.perf_counter() - t0:.1f}s)")
    return list(entries.values())


def measure(arch: str, image_sizes, widths, batch: int, iters: int) -> dict:
    import jax

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model

    if arch == "tiny":  # contract-test preset: 2 distinct blocks
        mc = ModelConfig(arch="mobilenet_v2", num_classes=8, dropout=0.0,
                         block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2, "k": [3, 5]},
                                      {"t": 2, "c": 16, "n": 1, "s": 2}])
    else:
        mc = ModelConfig(arch=arch)
    base = image_sizes[0]
    net = get_model(mc, base)

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    entries = build_table(net, image_sizes, widths, batch, iters, log=log)
    dev = jax.devices()[0]
    return {
        "arch": arch,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "image_sizes": list(image_sizes),
        "widths": list(widths),
        "batch": batch,
        "iters": iters,
        "entries": entries,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default="mobilenet_v3_large")
    ap.add_argument("--image-sizes", default="224", help="comma ladder of NETWORK input sizes")
    ap.add_argument("--widths", default="0.375,0.6875,1.0",
                    help="expanded-channel width fractions per block (>=2 for a slope fit)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--iters", type=int, default=12, help="timed runs per (block, width)")
    ap.add_argument("--out", default="", help="also write the JSON artifact here")
    args = ap.parse_args(argv)
    widths = tuple(float(w) for w in args.widths.split(","))
    image_sizes = tuple(int(s) for s in args.image_sizes.split(","))

    from bench import stamp_provenance

    out = {
        "metric": f"{args.arch}_block_latency_table",
        "value": None,
        "unit": "entries",
        "vs_baseline": None,
        "vs_baseline_note": "a lookup-table artifact, not a throughput headline",
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        if len(widths) < 2:
            raise ValueError("need >= 2 widths to fit a latency-vs-channels slope")
        out.update(measure(args.arch, image_sizes, widths, max(1, args.batch),
                           max(1, args.iters)))
        out["value"] = float(len(out["entries"]))
    except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
        out["error"] = f"{type(e).__name__}: {e}"
    stamp_provenance(out)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
