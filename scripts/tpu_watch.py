"""Standing TPU session watcher (round-agnostic): poll the tunnel; on the
first alive window, run the round's queued hardware measurements unattended
and APPLY the written decision rule, so one alive window settles everything
without a human in the loop.

Generalizes the round-3 watcher (VERDICT r3 weak #3: artifact names and
deadline were hardcoded). The axon tunnel dies for whole rounds (~25 min
UNAVAILABLE per probe; PROFILE.md) but alive windows appear without warning
(round 2 got one). The watcher probes via ``bench.py --probe`` (150 s kill
separates alive from dead) and, when the backend comes up, runs sequentially,
ONE job at a time (never killing a started TPU process — a killed job can
wedge the tunnel for the rest of the session):

  1. scripts/bench_bn.py --out BENCH_BN_r{N}.json     (the standing A/B)
  2. decision step (this process, no JAX): apply PROFILE.md's >3% rule to
     the A/B rows and write BENCH_TUNING.json so every later `python
     bench.py` — including the round driver's — measures the winner.
     Decision recorded in BENCH_DECISION_r{N}.json either way.
  3. python bench.py > BENCH_TPU_r{N}.json             (headline metric,
     now under the tuned config)
  4. (--with-sweep) scripts/bench_bn.py --xla-flags-sweep
     --out BENCH_XLA_r{N}.json                          (flag sweep over the
     winning variant, VERDICT r3 #7)

Before starting a session it waits for any running pytest to finish (this
sandbox has ONE visible core; concurrent CPU load corrupts TPU timings).
Probes continue until the deadline; a SESSION only starts if its full
worst-case budget fits before the deadline, so nothing is mid-flight when
the round's driver wants the chip.

Usage: python scripts/tpu_watch.py --round 4 [--deadline-min 600]
       [--interval 60] [--allow-compute] [--with-sweep]
Log: stderr (redirect to a file; tail it for status).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, REPO)
from bench import PROBE_TIMEOUT_S, TUNING_PATH, run_probe  # noqa: E402  (the canonical probe: alive/failed/timeout trichotomy)

# Worst-case wall clock of one session attempt: quiet-CPU wait (capped
# below) + re-probe + A/B timeout + headline timeout (+ sweep timeout when
# enabled). PROBES keep running until the deadline (cheap, kill-safe); only
# a SESSION start is gated on this budget fitting before the deadline, so
# nothing is mid-flight when the round's driver wants the chip.
QUIET_WAIT_S = 1200
AB_TIMEOUT_S = 3000       # alive-tunnel A/B is ~20 min; 50 min => window died
HEADLINE_TIMEOUT_S = 6000  # above bench.py's own worst case (~4950 s): it
                           # self-bounds via probe/deadline/fallback, so this
                           # backstop should never fire on a live supervisor
# flag sweep: one child per flag set. The outer budget must cover EVERY
# child hitting its own timeout (the designed dead-window path records an
# error row per child and keeps going) — 5 default sets x SWEEP_CHILD_S
# + slack — or the outer kill would preempt the per-child handling and
# lose the decision step on rows already persisted.
SWEEP_CHILD_S = 600       # TPU child: ~34 s init + ~90 s compile + 20 iters
SWEEP_TIMEOUT_S = 5 * SWEEP_CHILD_S + 1200
# trace capture: ~60-step CLI run with the profiler window under the FINAL
# adopted config — the op-cost re-rank the next round's attack needs
TRACE_TIMEOUT_S = 1500

# PROFILE.md "Round 3" decision rule: a parity-safe variant must beat the
# exact/no-remat/no-dot baseline by >3% to become the bench default.
WIN_THRESHOLD = 1.03
# exact/folded/fused_vjp: bit-level-equivalent math; sdot: identical
# expressions with MXU-dot statistics (f32 accumulation-order rounding only,
# ~1e-7 — same class as folded's re-association)
PARITY_SAFE_MODES = ("exact", "folded", "fused_vjp", "sdot")
# the `compute` family (bf16 FMA normalize, incl. the compute_sdot
# composite) needs the top-1-parity argument before defaulting —
# tests/test_acceptance_mbv2.py's bn_mode prediction-agreement test supplies
# it; pass --allow-compute once that test is green on the round's tree.
COMPUTE_MODES = ("compute", "compute_sdot")
LOSS_SANITY_ABS = 0.02    # same data/key => losses near-identical across variants

START_TIME = time.time()
# monotonic deadline set by main(); best-effort stages (sweep, trace) check
# it so a dying window can never leave them mid-flight when the round's
# driver wants the chip
T_END = None

# --cpu-rehearsal (VERDICT r4 next #1): the unattended A/B → decide →
# headline → sweep → trace chain had only ever been exercised piecewise;
# its first real execution must not double as its integration test. In
# rehearsal mode run_session runs ONCE against the CPU backend (bench
# children smoke-scale themselves), artifacts get a _cpu_rehearsal suffix,
# and every tuning write is redirected to a rehearsal file so the
# production BENCH_TUNING.json is never touched.
CPU_MODE = False
EXPECTED_PLATFORM = "tpu"
# where session artifacts (BENCH_*/TRACE_*) land; the rehearsal test points
# this at a tmp dir (env override) so scoped rehearsals cannot litter the
# repo root
ARTIFACT_DIR = os.environ.get("TPU_WATCH_ARTIFACT_DIR") or REPO


def _time_left_for(seconds: float, label: str) -> bool:
    if T_END is not None and time.monotonic() + seconds >= T_END:
        log(f"skipping {label}: worst case ({seconds:.0f}s) does not fit before the deadline")
        return False
    return True


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def probe_alive() -> bool:
    status, info = run_probe()
    if status == "alive" and info.get("platform") == "tpu":
        log(f"ALIVE: {info}")
        return True
    log(f"probe status: {status}")
    return False


def wait_for_quiet_cpu(max_wait_s=QUIET_WAIT_S):
    t0 = time.monotonic()
    while time.monotonic() - t0 < max_wait_s:
        r = subprocess.run(["pgrep", "-f", "pytest"], capture_output=True)
        if r.returncode != 0:
            return
        log("pytest running; delaying TPU session for quiet CPU")
        time.sleep(60)
    log("quiet-CPU wait expired; proceeding anyway")


def _fresh_complete_ab(path: str) -> bool:
    if not (os.path.exists(path) and os.path.getmtime(path) >= START_TIME):
        return False
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    return d.get("partial") is False and d.get("platform") == EXPECTED_PLATFORM


# three owners of BENCH_TUNING.json keys, each preserving the others' keys
# on every path: the A/B variant decision, the dispatch-probe decision
# (NOT in _AB_KEYS: a no-win A/B round whose probe died must leave a
# previously MEASURED dispatch adoption alone — _decide_dispatch is the
# only writer/clearer of these), and the flag-sweep decision
_AB_KEYS = ("bn_mode", "remat", "remat_policy", "conv1x1_dot", "source", "provisional")
_DISPATCH_KEYS = ("steps_per_dispatch", "steps_per_dispatch_source")
_FLAG_KEYS = ("flags", "flags_source")
# dispatch-tax adoption: when the A/B's --dispatch-probe row shows the
# per-step dispatch overhead is a meaningful slice of the chained step
# time, turn on modest multi-step dispatch in the tuned config (bench.py
# measures it grouped; cli train.steps_per_dispatch is the production
# knob). k=4 amortizes ~75% of the tax at bounded compile-time cost.
DISPATCH_TAX_THRESHOLD = 0.03
DISPATCH_K = 4


def _read_tuning() -> dict:
    try:
        with open(TUNING_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _write_tuning(tuning: dict):
    """Persist the tuning dict; an EMPTY dict removes the file (a leftover
    file with no keys would still read as 'tuned' in logs)."""
    if tuning:
        with open(TUNING_PATH, "w") as f:
            json.dump(tuning, f, indent=1)
            f.write("\n")
    else:
        try:
            os.remove(TUNING_PATH)
        except FileNotFoundError:
            pass


def _drop_stale_ab_tuning(why: str):
    tuning = _read_tuning()
    kept = {k: v for k, v in tuning.items() if k not in _AB_KEYS}
    if kept != tuning or not kept:
        _write_tuning(kept)
    log(f"decision: {why}; A/B tuning keys cleared"
        + (f" (kept {sorted(kept)})" if kept else ""))


def decide(ab_path: str, decision_path: str, allow_compute: bool) -> None:
    """Apply the >3% rule to a completed A/B and persist the outcome.

    Writes BENCH_TUNING.json (consumed by bench.py's worker) only on a win;
    always writes the decision record so a no-move result is a documented
    negative, not silence. Pure host-side JSON work — safe to re-run."""
    with open(ab_path) as f:
        ab = json.load(f)
    if ab.get("contention_invalidated"):
        # ADVICE r5: an A/B measured under host contention (e.g. the r5
        # 623ms-vs-36ms baseline skew) must never steer an adoption — its
        # ratios compare incomparable regimes. Record the refusal.
        decision = {
            "rule": "contention_invalidated artifact: adoption refused",
            "ab_source": os.path.basename(ab_path),
            "contention_note": ab.get("contention_note"),
            "baseline": None, "winner": None, "adopted": False,
        }
        with open(decision_path, "w") as f:
            json.dump(decision, f, indent=1)
            f.write("\n")
        _drop_stale_ab_tuning("A/B artifact is contention-invalidated")
        return
    rows = ab.get("rows", [])
    base = next((r for r in rows if r["bn_mode"] == "exact" and r["remat"] == "off"
                 and not r["conv1x1_dot"]), None)
    decision = {
        "rule": f"PROFILE.md round-3: >{(WIN_THRESHOLD-1)*100:.0f}% over exact/no-remat baseline; "
                f"parity-safe modes {PARITY_SAFE_MODES}"
                + (" + compute (parity test green)" if allow_compute else ""),
        "ab_source": os.path.basename(ab_path),
        "baseline": base,
        "winner": None,
        "adopted": False,
    }
    if base is not None:
        eligible_modes = PARITY_SAFE_MODES + (COMPUTE_MODES if allow_compute else ())
        best, best_speedup = None, WIN_THRESHOLD
        for r in rows:
            if r["bn_mode"] not in eligible_modes:
                continue
            if abs(r["loss"] - base["loss"]) > LOSS_SANITY_ABS:
                log(f"decision: skipping {r['bn_mode']}/{r['remat']}/dot={r['conv1x1_dot']}: "
                    f"loss {r['loss']} vs baseline {base['loss']} fails sanity")
                continue
            speedup = base["ms_per_step"] / r["ms_per_step"]
            if speedup > best_speedup:
                best, best_speedup = r, speedup
        if best is not None:
            decision["winner"] = dict(best, speedup_vs_exact=round(best_speedup, 4))
            decision["adopted"] = True
            provisional = None
            if best["bn_mode"] in COMPUTE_MODES:
                # VERDICT r4 weak #4: the compute family's parity argument is
                # a synthetic-JPEG fixture + toy convergence, not a real
                # top-1 — record that the adoption is provisional until the
                # env-gated real-data test (test_acceptance_mbv2) has run.
                # Written into BOTH the decision record and the tuning file:
                # the tuning file is what production runs actually consume
                # (train.tuning_file surfaces it in the startup provenance).
                provisional = (
                    "compute-family win adopted on the synthetic-fixture parity "
                    "argument; re-validate with the YAMT_IMAGENET_VAL_DIR real-data "
                    "top-1-delta test before a production 350-epoch run")
                decision["provisional"] = provisional
            tuning = _read_tuning()  # preserve sweep-owned flags keys
            tuning.pop("provisional", None)  # stale marker from an earlier win
            # a fresh clean-window adoption supersedes an earlier
            # contention-invalidated one: drop the stale warning keys
            tuning.pop("contention_invalidated", None)
            tuning.pop("contention_note", None)
            tuning.update({
                "bn_mode": best["bn_mode"],
                "remat": best["remat"] != "off",
                "remat_policy": best["remat"] if best["remat"] == "save_conv" else "full",
                "conv1x1_dot": bool(best["conv1x1_dot"]),
                "source": f"{os.path.basename(ab_path)} ({best_speedup:.3f}x vs exact, "
                          f"{ab.get('device_kind')})",
            })
            if provisional:
                tuning["provisional"] = provisional
            _write_tuning(tuning)
            log(f"decision: ADOPTED {tuning}")
        else:
            # a stale winner from an earlier round must not keep steering
            # bench.py after THIS A/B declined to adopt anything — the
            # decision record and the measured config would contradict
            _drop_stale_ab_tuning("no variant beat the threshold (negative result recorded)")
    else:
        _drop_stale_ab_tuning("A/B has no baseline row")
    _decide_dispatch(rows, decision)
    with open(decision_path, "w") as f:
        json.dump(decision, f, indent=1)
        f.write("\n")


def _decide_dispatch(rows, decision: dict) -> None:
    """Adopt multi-step dispatch from the A/B's --dispatch-probe row: when
    the measured per-step dispatch tax exceeds DISPATCH_TAX_THRESHOLD of
    the chained step time, set steps_per_dispatch=DISPATCH_K in the tuning
    (bench.py measures grouped; cli train.steps_per_dispatch is the
    production knob). Independent of which bn_mode variant won — the tax
    applies to every config. No probe row (probe died): leave any
    previously-measured value alone."""
    probe = next((r for r in rows if "dispatch_tax_ms" in r), None)
    if probe is None or not probe.get("ms_per_step_chained"):
        decision["dispatch_probe"] = None
        return
    frac = probe["dispatch_tax_ms"] / probe["ms_per_step_chained"]
    decision["dispatch_probe"] = dict(probe, tax_fraction=round(frac, 4))
    tuning = _read_tuning()
    if probe["dispatch_tax_ms"] > 0 and frac > DISPATCH_TAX_THRESHOLD:
        tuning["steps_per_dispatch"] = DISPATCH_K
        tuning["steps_per_dispatch_source"] = (
            f"dispatch probe: {probe['dispatch_tax_ms']} ms tax = {frac:.1%} "
            f"of the chained step")
        decision["dispatch_adopted"] = True
        log(f"decision: dispatch tax {frac:.1%} -> steps_per_dispatch={DISPATCH_K}")
    else:
        for key in _DISPATCH_KEYS:
            tuning.pop(key, None)
        decision["dispatch_adopted"] = False
        log(f"decision: dispatch tax {frac:.1%} below threshold; single-step dispatch kept")
    _write_tuning(tuning)


def decide_sweep(sweep_path: str, decision_path: str) -> None:
    """Apply the >3% rule to a completed flag sweep: merge the winning flag
    string into BENCH_TUNING.json's 'flags' key (bench.py applies it to TPU
    workers via env). A no-win clears any stale 'flags' entry; other tuning
    keys are untouched."""
    with open(sweep_path) as f:
        sweep = json.load(f)
    rows = [r for r in sweep.get("rows", []) if "ms_per_step" in r]
    base = next((r for r in rows if r["flags"] == ""), None)
    decision = {"rule": f">{(WIN_THRESHOLD-1)*100:.0f}% over the no-flags baseline",
                "sweep_source": os.path.basename(sweep_path),
                "baseline": base, "winner": None, "adopted": False}
    best, best_speedup = None, WIN_THRESHOLD
    if base is not None:
        for r in rows:
            if not r["flags"]:
                continue
            # same loss-sanity gate decide() applies to A/B variants
            # (ADVICE r4 #3): a fusion/scheduler flag can change reduction
            # order or worse — a flag set that perturbs the measured loss
            # must not win on speed alone and steer every later bench
            # (rows from older sweeps may lack loss; only compare when both
            # sides carry one)
            if (base.get("loss") is not None and r.get("loss") is not None
                    and abs(r["loss"] - base["loss"]) > LOSS_SANITY_ABS):
                log(f"sweep decision: skipping {r['flags']!r}: loss "
                    f"{r['loss']} vs baseline {base['loss']} fails sanity")
                continue
            speedup = base["ms_per_step"] / r["ms_per_step"]
            if speedup > best_speedup:
                best, best_speedup = r, speedup
    tuning = _read_tuning()  # preserve A/B-owned keys
    if best is not None:
        decision["winner"] = dict(best, speedup_vs_noflags=round(best_speedup, 4))
        decision["adopted"] = True
        tuning["flags"] = best["flags"]
        tuning["flags_source"] = (f"{os.path.basename(sweep_path)} "
                                  f"({best_speedup:.3f}x vs no-flags)")
        log(f"sweep decision: ADOPTED flags {best['flags']!r}")
    else:
        for k in _FLAG_KEYS:
            tuning.pop(k, None)
        log("sweep decision: no flag set beat the threshold; flags cleared")
    _write_tuning(tuning)  # empty dict removes the file — never leaves stale flags
    with open(decision_path, "w") as f:
        json.dump(decision, f, indent=1)
        f.write("\n")


def _run_job(cmd: list[str], timeout_s: int, label: str, env: dict | None = None):
    """Run one TPU job to its own completion (timeout only catches a window
    that died mid-job, leaving the process stuck in dead-tunnel init — the
    safe-to-kill case, NOT a running TPU computation)."""
    log(f"session: {label} starting")
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout_s,
                           env=env)
    except subprocess.TimeoutExpired:
        log(f"{label} exceeded its window (closed mid-session?); will keep watching")
        return None
    # stdout tail too: when a window's headline emits a fallback/value=null
    # JSON, that line is the only post-mortem of the burned window
    log(f"{label} rc={r.returncode}; stdout tail: {r.stdout[-1000:]}; "
        f"stderr tail: {r.stderr[-2000:]}")
    return r


def _tuning_has_flags() -> bool:
    try:
        with open(TUNING_PATH) as f:
            return "flags" in json.load(f)
    except (OSError, json.JSONDecodeError):
        return False


def _record_headline(r, headline_path: str) -> bool:
    """Persist a completed bench.py run's JSON line as the round headline.

    Only a REAL TPU measurement counts (bench.py prints structured error/
    fallback JSON too, and recording that would end the watch with a corrupt
    headline), and a re-run never overwrites a BETTER number from earlier in
    the same session (a flag 'win' on one variant can still lose end-to-end)."""
    if r is None or r.returncode != 0:
        return False
    headline = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            cand = json.loads(line)
            if isinstance(cand, dict) and "metric" in cand:
                headline = cand
                break
        except json.JSONDecodeError:
            continue
    if headline is None or headline.get("value") is None or headline.get("platform") != EXPECTED_PLATFORM:
        return False
    try:
        with open(headline_path) as f:
            prev = json.load(f)
        if (prev.get("value") or 0) >= headline["value"] and os.path.getmtime(headline_path) >= START_TIME:
            log(f"headline re-run ({headline['value']}) did not beat the session's "
                f"earlier {prev['value']}; keeping the better artifact")
            return True
    except (OSError, json.JSONDecodeError):
        pass
    with open(headline_path, "w") as f:
        json.dump(headline, f)
        f.write("\n")
    log(f"headline secured: {headline.get('value')} img/s/chip")
    return True


def run_trace(tag: str) -> None:
    """Best-effort trace capture under the FINAL adopted config (tuning keys
    as CLI overrides, adopted flags in the env): ~60 steps of the headline
    recipe with the profiler window, decoded to TRACE_OPS_{tag}.txt — the
    op-cost re-rank the next round's attack is planned from."""
    tuning = _read_tuning()
    trace_dir = os.path.join(ARTIFACT_DIR, "traces", tag)
    # steps_per_epoch for dataset=fake is fake_train_size/batch: pin the
    # ratio to exactly 60 steps so the profiler window (30..50) actually
    # opens (a fractional-epoch guess here once produced a 1-step run and
    # no trace at all). Rehearsal keeps the same 60-step geometry at
    # CPU-feasible shapes.
    batch, train_size = (8, 480) if CPU_MODE else (256, 15360)
    cmd = [sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.train",
           "app:yet_another_mobilenet_series_tpu/apps/mobilenet_v3_large.yml",
           "data.dataset=fake", "data.loader=synthetic",
           f"data.fake_train_size={train_size}", f"train.batch_size={batch}",
           "train.epochs=1", "train.eval_every_epochs=0",
           "train.profile_start_step=30", "train.profile_num_steps=20",
           f"train.log_dir={trace_dir}"]
    if CPU_MODE:
        cmd.append("data.image_size=32")
    for cfg_key, t_key in (("train.bn_mode", "bn_mode"),
                           ("train.conv1x1_dot", "conv1x1_dot"),
                           ("train.remat", "remat"),
                           ("train.remat_policy", "remat_policy")):
        if t_key in tuning:
            v = tuning[t_key]
            cmd.append(f"{cfg_key}={str(v).lower() if isinstance(v, bool) else v}")
    env = None
    if CPU_MODE:
        # the CLI child cannot call jax.config.update for itself: force CPU
        # by dropping the axon sitecustomize from PYTHONPATH (it force-
        # selects the tpu platform) and selecting the cpu backend
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if tuning.get("flags"):
        try:
            from bench import apply_flags_env

            env = apply_flags_env(env if env is not None else os.environ.copy(),
                                  tuning["flags"])
        except ValueError as e:
            log(f"trace: ignoring malformed tuned flags: {e}")
    r = _run_job(cmd, TRACE_TIMEOUT_S, "trace capture", env=env)
    if r is None or r.returncode != 0:
        return
    rd = _run_job([sys.executable, os.path.join(REPO, "scripts", "trace_ops.py"),
                   os.path.join(trace_dir, "trace"), "40"],
                  600, "trace decode")
    if rd is not None and rd.returncode == 0 and rd.stdout.strip():
        out_path = os.path.join(ARTIFACT_DIR, f"TRACE_OPS_{tag}.txt")
        with open(out_path, "w") as f:
            f.write(f"# op breakdown under config {tuning or 'baseline'}\n")
            f.write(rd.stdout)
        log(f"trace decoded -> {os.path.basename(out_path)}")


def _tag(args) -> str:
    return f"r{args.round}" + ("_cpu_rehearsal" if CPU_MODE else "")


def run_session(args) -> bool:
    """Returns True only if the round's A/B + headline artifacts were actually
    produced — a False lets the caller keep watching for the next window."""
    tag = _tag(args)
    ab_path = os.path.join(ARTIFACT_DIR, f"BENCH_BN_{tag}.json")
    decision_path = os.path.join(ARTIFACT_DIR, f"BENCH_DECISION_{tag}.json")
    # a previous session THIS RUN may have secured the A/B — don't spend a
    # fresh (possibly short) alive window redoing it. A pre-existing (stale)
    # artifact from older code must NOT suppress measurement (hence the
    # created-after-watcher-start check), and neither may a PARTIAL one
    # from a mid-sweep crash (bench_bn writes incrementally).
    if _fresh_complete_ab(ab_path):
        log("fresh complete A/B artifact already present; skipping straight to decision")
    else:
        ab_cmd = [sys.executable, os.path.join(REPO, "scripts", "bench_bn.py"),
                  "--dispatch-probe", "--out", ab_path]
        if args.variants:
            ab_cmd += ["--variants", args.variants]
        if CPU_MODE:
            ab_cmd.append("--cpu")  # bench_bn smoke-scales itself on CPU
        r1 = _run_job(ab_cmd, AB_TIMEOUT_S, "bench_bn A/B")
        # the ARTIFACT gates the session, not the exit code: the variants
        # emit a complete artifact before the best-effort dispatch probe, so
        # a probe-stage death must not discard 11 measured variants
        if not _fresh_complete_ab(ab_path):
            log("A/B failed or incomplete (window closed?); will keep watching")
            return False
        if r1 is None:
            # the probe hung and _run_job KILLED it — and a killed TPU job
            # can wedge the tunnel (module header). Bank the A/B via the
            # decision step, but do NOT launch more TPU stages into a
            # possibly-wedged claim; the next alive window fast-paths
            # straight to the headline off the fresh artifact.
            log("A/B artifact complete but the probe stage was KILLED at timeout; "
                "running the decision, then abandoning this window")
            try:
                decide(ab_path, decision_path, args.allow_compute)
            except Exception as e:
                log(f"decision step failed ({type(e).__name__}: {e})")
            return False
        if r1.returncode != 0:
            log("A/B artifact complete but the probe stage died (nonzero exit); "
                "continuing the session")
    try:
        decide(ab_path, decision_path, args.allow_compute)
    except Exception as e:  # a decision bug must not cost the alive window
        log(f"decision step failed ({type(e).__name__}: {e}); headline runs on current defaults")

    headline_cmd = [sys.executable, os.path.join(REPO, "bench.py")]
    if CPU_MODE:
        headline_cmd.append("--cpu")  # direct CPU smoke worker, no supervisor
    headline_path = os.path.join(ARTIFACT_DIR, f"BENCH_TPU_{tag}.json")
    r2 = _run_job(headline_cmd, HEADLINE_TIMEOUT_S, "headline bench.py")
    if not _record_headline(r2, headline_path):
        log(f"headline run produced no {EXPECTED_PLATFORM} measurement; will rewatch")
        return False

    if args.with_sweep and _time_left_for(SWEEP_TIMEOUT_S + HEADLINE_TIMEOUT_S, "xla flag sweep"):
        sweep_path = os.path.join(ARTIFACT_DIR, f"BENCH_XLA_{tag}.json")
        sweep_cmd = [sys.executable, os.path.join(REPO, "scripts", "bench_bn.py"),
                     "--xla-flags-sweep", "--child-timeout", str(SWEEP_CHILD_S),
                     "--out", sweep_path]
        if args.flag_sets is not None:
            sweep_cmd += ["--flag-sets", args.flag_sets]
        if CPU_MODE:
            sweep_cmd.append("--cpu")
        _run_job(sweep_cmd, SWEEP_TIMEOUT_S, "xla flag sweep")
        # sweep is best-effort: A/B + headline already make the session a win.
        # The artifact persists incrementally, so decide on whatever rows
        # exist — even after a mid-sweep window death or an outer timeout
        # (the baseline row runs first, so any fresh artifact can anchor the
        # rule; decide_sweep clears flags when no winner is present).
        if os.path.exists(sweep_path) and os.path.getmtime(sweep_path) >= START_TIME:
            try:
                decide_sweep(sweep_path, os.path.join(
                    ARTIFACT_DIR, f"BENCH_DECISION_XLA_{tag}.json"))
            except Exception as e:
                log(f"sweep decision failed ({type(e).__name__}: {e}); flags unchanged")
            # a flag win changes what the headline SHOULD measure — re-run
            # bench.py once so BENCH_TPU_r{N} reflects the adopted config
            if _tuning_has_flags():
                r4 = _run_job(headline_cmd, HEADLINE_TIMEOUT_S,
                              "headline re-run under adopted flags")
                _record_headline(r4, headline_path)
    # trace LAST: it captures the op mix of whatever config the session
    # adopted, which is what the next round plans from
    if _time_left_for(TRACE_TIMEOUT_S + 600, "trace capture"):
        run_trace(tag)
    log("session complete")
    return True


def main():
    global CPU_MODE, EXPECTED_PLATFORM, TUNING_PATH, T_END
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, required=True,
                    help="round number N for BENCH_*_r{N}.json artifact names")
    ap.add_argument("--deadline-min", type=float, default=240.0,
                    help="stop starting new probes/sessions after this many minutes")
    ap.add_argument("--interval", type=float, default=60.0, help="sleep between dead probes")
    ap.add_argument("--allow-compute", action="store_true",
                    help="let the decision rule adopt bn_mode=compute (requires the "
                         "bn_mode prediction-agreement test to be green on this tree)")
    ap.add_argument("--with-sweep", action="store_true",
                    help="after a secured headline, run the XLA flag sweep too")
    ap.add_argument("--cpu-rehearsal", action="store_true",
                    help="run ONE full unattended session against the CPU backend "
                         "(smoke-scaled, artifacts suffixed _cpu_rehearsal, tuning "
                         "writes redirected) — integration-proves the A/B -> decide "
                         "-> headline -> sweep -> trace chain without hardware")
    ap.add_argument("--variants", default=None,
                    help="forwarded to bench_bn --variants (rehearsal/test scoping)")
    ap.add_argument("--flag-sets", default=None,
                    help="forwarded to bench_bn --flag-sets (rehearsal/test scoping)")
    args = ap.parse_args()
    if args.cpu_rehearsal:
        CPU_MODE, EXPECTED_PLATFORM = True, "cpu"
        # every writer in this process (_write_tuning) and every bench child
        # (BENCH_TUNING_PATH env, honored by bench.TUNING_PATH) uses the
        # rehearsal file — the production BENCH_TUNING.json is never touched
        TUNING_PATH = os.path.join(ARTIFACT_DIR, "BENCH_TUNING_cpu_rehearsal.json")
        os.environ["BENCH_TUNING_PATH"] = TUNING_PATH
        _write_tuning({})  # clean slate: drop any previous rehearsal's adoption
        T_END = time.monotonic() + args.deadline_min * 60
        ok = run_session(args)
        log(f"cpu rehearsal {'complete' if ok else 'FAILED'}")
        sys.exit(0 if ok else 1)
    # gate session START on the MANDATORY stages' worst case only; the
    # best-effort stages (sweep + its headline re-run, trace) each re-check
    # the deadline themselves and are skipped when they no longer fit
    session_budget = QUIET_WAIT_S + PROBE_TIMEOUT_S + AB_TIMEOUT_S + HEADLINE_TIMEOUT_S
    t_end = T_END = time.monotonic() + args.deadline_min * 60
    n = 0
    # probes run until the deadline (cheap, kill-safe); only a SESSION start
    # is gated on the full budget fitting before t_end, so a late-found
    # window is still logged even when there is no time left to use it.
    # even a PROBE must fully fit before the deadline: a mid-flight probe at
    # t_end would contend with the round driver's own bench on the tunnel
    while time.monotonic() + PROBE_TIMEOUT_S < t_end:
        n += 1
        log(f"probe #{n}")
        if probe_alive():
            if time.monotonic() + session_budget >= t_end:
                log("ALIVE WINDOW FOUND but no time left for a full session before the deadline; exiting")
                return
            wait_for_quiet_cpu()
            # the quiet-CPU wait can outlive an alive window: re-confirm
            # before burning a ~25-min dead-tunnel init inside the session
            if probe_alive() and run_session(args):
                return
            log("window closed or session failed; resuming watch")
            continue
        log("dead; sleeping")
        time.sleep(args.interval)
    log("deadline reached without an alive window")


if __name__ == "__main__":
    main()
