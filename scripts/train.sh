#!/usr/bin/env bash
# Single-host launcher (reference: scripts/*.sh wrapping
# torch.distributed.launch, SURVEY.md §2 #15). On TPU there is one process
# per HOST, not per chip — the in-process mesh covers all local chips.
#
# Usage: scripts/train.sh apps/mobilenet_v3_large.yml [key=value ...]
set -euo pipefail
APP=${1:?usage: train.sh <app.yml> [overrides...]}
shift
exec python -m yet_another_mobilenet_series_tpu.cli.train "app:${APP}" "$@"
