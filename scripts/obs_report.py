#!/usr/bin/env python
"""Render a run's telemetry (metrics.jsonl + obs_registry.json +
hang_report.json if present) into a text summary — the post-run half of
docs/OBSERVABILITY.md. Pure stdlib file reading, no jax/tf import, so it
runs anywhere (CI after the tier-1 gate, a laptop against rsynced logs).

``--requests`` additionally renders the REQUEST view from obs_trace.json:
per-request waterfalls (queued / in-flight phase durations reconstructed
from the async ``b``/``e`` events serve/context.py emits, one row per
request id) and a per-phase quantile table (p50/p95/p99 straight from the
bucketed registry histograms — the same numbers ``GET /metrics`` exposes).

``--fleet`` renders the FLEET view from a cli/fleet.py run's log_dir: the
replica-slot layout, the merged cross-process trace if trace_merge.py built
one (with each lane's clock-alignment offset), and every
``incident_<reason>.json`` the flight recorder dumped (obs/fleet.py) —
trigger reason, brownout level, the event-ring census, the federated
window p99 / SLO burn rates at dump time, and the last ring events.

Usage: python scripts/obs_report.py [--requests] [--fleet] [--max-requests N] <log_dir>
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _load_jsonl(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


# histogram-suffix columns for the quantile tables (obs/registry.py snapshot
# expansion); values are seconds, rendered in ms
_Q_COLS = ("p50", "p95", "p99", "min", "max")


def _quantile_table(snap: dict, names: list[tuple[str, str]]) -> list[str]:
    """Aligned per-phase quantile rows for every histogram in ``names``
    ((registry name, label)) that has data."""
    rows = []
    header = f"  {'phase':<28} {'count':>7} " + " ".join(f"{c + '_ms':>9}" for c in _Q_COLS)
    for name, label in names:
        count = snap.get(f"{name}.count")
        if not count:
            continue
        cells = " ".join(f"{snap.get(f'{name}.{c}', 0.0) * 1e3:>9.3f}" for c in _Q_COLS)
        rows.append(f"  {label:<28} {count:>7.0f} {cells}")
    return [header] + rows if rows else []


def _request_waterfalls(trace_path: str, max_requests: int) -> list[str]:
    """Per-request phase waterfalls from the trace's async b/e events."""
    with open(trace_path) as f:
        events = json.load(f).get("traceEvents", [])
    # (id, name) -> [begin_ts, end_ts] in µs; ids are request ids
    spans: dict[tuple[int, str], list[float | None]] = {}
    args_by_id: dict[int, dict] = {}
    tids_by_id: dict[int, set] = {}
    for e in events:
        if e.get("ph") not in ("b", "e") or "id" not in e:
            continue
        key = (e["id"], e["name"])
        slot = spans.setdefault(key, [None, None])
        slot[0 if e["ph"] == "b" else 1] = e["ts"]
        if e.get("args"):  # "b" carries cls/deadline, "e" carries outcome
            args_by_id.setdefault(e["id"], {}).update(e["args"])
        tids_by_id.setdefault(e["id"], set()).add(e["tid"])
    rids = sorted({rid for rid, _ in spans})
    if not rids:
        return ["  no request events in the trace (obs.trace off, or no served load)"]
    lines = [f"  {len(rids)} request(s) in the trace ring; "
             f"showing {min(len(rids), max_requests)} "
             f"(admit -> queued -> in-flight -> done, host µs timestamps)"]
    for rid in rids[:max_requests]:
        def _dur(name):
            b, e = spans.get((rid, name), (None, None))
            return (e - b) / 1e3 if b is not None and e is not None else None
        total = _dur("serve/request")
        queued = _dur("serve/queued")
        inflight = _dur("serve/inflight")
        a = args_by_id.get(rid, {})
        outcome = a.get("outcome", "?")
        parts = [f"  #{rid:<6} class={a.get('cls', '?'):<12}"]
        for label, v in (("total", total), ("queued", queued), ("inflight", inflight)):
            parts.append(f"{label}={v:.2f}ms" if v is not None else f"{label}=?")
        parts.append(f"threads={len(tids_by_id.get(rid, ()))}")
        lines.append(" ".join(parts) + (f" [{outcome}]" if outcome != "?" else ""))
    return lines


def _fleet_section(log_dir: str) -> list[str]:
    """The fleet view: replica layout, merged trace, incident artifacts."""
    lines = ["\n## fleet"]
    replica_dirs = sorted(
        d for d in glob.glob(os.path.join(log_dir, "r*")) if os.path.isdir(d))
    traced = [d for d in replica_dirs
              if os.path.exists(os.path.join(d, "obs_trace.json"))]
    lines.append(f"  replica slots: {len(replica_dirs)} "
                 f"({len(traced)} with traces)")
    merged = os.path.join(log_dir, "merged_trace.json")
    if os.path.exists(merged):
        with open(merged) as f:
            doc = json.load(f)
        procs = doc.get("processes", [])
        lines.append(f"  merged trace: {merged} "
                     f"({len(doc.get('traceEvents', []))} events, "
                     f"{len(procs)} process lanes) — open in ui.perfetto.dev")
        for p in procs:
            lines.append(f"    {p.get('process_name', '?'):<24} "
                         f"offset {p.get('offset_us', 0.0) / 1e3:+.3f} ms  "
                         f"{p.get('file', '')}")
    elif traced or os.path.exists(os.path.join(log_dir, "obs_trace.json")):
        lines.append("  merged trace: not built — "
                     f"python scripts/trace_merge.py {log_dir}")
    incidents = sorted(glob.glob(os.path.join(log_dir, "incident_*.json")))
    if not incidents:
        lines.append("  incidents: none recorded "
                     "(no ejection / brownout / fast-burn trigger fired)")
    for path in incidents:
        with open(path) as f:
            doc = json.load(f)
        events = doc.get("events", [])
        lines.append(f"  !! incident: {os.path.basename(path)} — "
                     f"reason = {doc.get('reason')}, "
                     f"brownout L{doc.get('brownout_level', 0)}, "
                     f"{len(events)} ring events")
        kinds: dict[str, int] = {}
        for e in events:
            kinds[str(e.get("kind", "?"))] = kinds.get(str(e.get("kind", "?")), 0) + 1
        if kinds:
            lines.append("    events: " + ", ".join(
                f"{k} x{v}" for k, v in sorted(kinds.items())))
        fleet = doc.get("fleet") or {}
        for cls, v in sorted((fleet.get("window_p99_s") or {}).items()):
            if v:
                lines.append(f"    window p99 [{cls}] = {v * 1e3:.2f} ms")
        slo = fleet.get("slo") or {}
        if slo:
            lines.append(
                f"    slo: burn short {slo.get('burn_short', 0):.2f} / "
                f"long {slo.get('burn_long', 0):.2f}"
                f"{' — FAST BURN' if slo.get('fast_burn') else ''} "
                f"(target p99 {slo.get('target_p99_ms', 0):.0f} ms, "
                f"budget {slo.get('error_budget', 0):.3g})")
        reps = fleet.get("replicas") or {}
        if reps:
            lines.append(f"    federated replicas at dump: {len(reps)} "
                         f"({', '.join(sorted(reps))})")
        for e in events[-5:]:
            extras = " ".join(f"{k}={v}" for k, v in e.items()
                              if k not in ("t_unix", "kind"))
            lines.append(f"    last: {e.get('kind')}"
                         + (f" {extras}" if extras else ""))
    return lines


def summarize(log_dir: str, requests: bool = False, max_requests: int = 20,
              fleet: bool = False) -> str:
    lines = [f"# obs report: {log_dir}"]

    metrics_path = os.path.join(log_dir, "metrics.jsonl")
    if os.path.exists(metrics_path):
        rows = _load_jsonl(metrics_path)
        if rows:
            lines.append(f"\n## metrics.jsonl ({len(rows)} rows, "
                         f"steps {rows[0].get('step', '?')}..{rows[-1].get('step', '?')})")
            train_rows = [r for r in rows if any(k.startswith("train/") for k in r)]
            eval_rows = [r for r in rows if any(k.startswith("eval/") for k in r)]
            if train_rows:
                last = train_rows[-1]
                for key in ("train/loss", "train/images_per_sec", "train/images_per_sec_per_chip"):
                    if key in last:
                        lines.append(f"  last {key} = {last[key]:.6g} (step {last['step']})")
            if eval_rows:
                best = max(eval_rows, key=lambda r: r.get("eval/top1", float("-inf")))
                if "eval/top1" in best:
                    lines.append(f"  best eval/top1 = {best['eval/top1']:.6g} (step {best['step']})")
                last = eval_rows[-1]
                for key in ("eval/top1", "eval/loss"):
                    if key in last:
                        lines.append(f"  last {key} = {last[key]:.6g} (step {last['step']})")
        else:
            lines.append("\n## metrics.jsonl: empty")
    else:
        lines.append("\n## metrics.jsonl: missing")

    reg_path = os.path.join(log_dir, "obs_registry.json")
    if os.path.exists(reg_path):
        with open(reg_path) as f:
            snap = json.load(f)
        lines.append(f"\n## registry snapshot ({len(snap)} metrics)")
        for name in sorted(snap):
            lines.append(f"  {name} = {snap[name]:.6g}")
        if any(k.startswith("serve.") for k in snap):
            # serving run (docs/SERVING.md): derive the headline numbers from
            # the histograms the engine/batcher populate
            lines.append("\n## serving")
            lines.append(
                "  requests = {:.0f}, completed = {:.0f}, shed = {:.0f}, "
                "rejected = {:.0f}".format(
                    snap.get("serve.requests", 0), snap.get("serve.completed", 0),
                    snap.get("serve.shed_deadline", 0), snap.get("serve.rejected_full", 0))
            )
            for h, label in (("serve.queue_wait_seconds", "queue wait"),
                             ("serve.run_seconds", "run latency"),
                             ("serve.dispatch_seconds", "dispatch"),
                             ("serve.h2d_seconds", "h2d transfer"),
                             ("serve.slot_wait_seconds", "slot fence wait"),
                             ("serve.dispatch_to_complete_seconds", "dispatch->complete")):
                if snap.get(f"{h}.count"):
                    lines.append(
                        f"  {label}: p50 {snap.get(f'{h}.p50', 0) * 1e3:.2f} / "
                        f"p95 {snap.get(f'{h}.p95', 0) * 1e3:.2f} / "
                        f"p99 {snap.get(f'{h}.p99', 0) * 1e3:.2f} ms, "
                        f"min {snap.get(f'{h}.min', 0) * 1e3:.2f} ms, "
                        f"mean {snap.get(f'{h}.mean', 0) * 1e3:.2f} ms, "
                        f"max {snap.get(f'{h}.max', 0) * 1e3:.2f} ms over {snap[f'{h}.count']:.0f}"
                    )
            if snap.get("serve.batch_size.count"):
                lines.append(
                    f"  batch size: mean {snap['serve.batch_size.mean']:.2f}, "
                    f"max {snap['serve.batch_size.max']:.0f}"
                )
            if snap.get("serve.shed_at_completion"):
                lines.append(
                    f"  shed at completion: {snap['serve.shed_at_completion']:.0f} "
                    "(deadline passed while the batch executed)"
                )
            if snap.get("serve.fused_dispatches"):
                lines.append(
                    f"  fused dispatches: {snap['serve.fused_dispatches']:.0f} "
                    f"covering {snap.get('serve.fused_chunks', 0):.0f} chunks "
                    "(whole-request lax.scan pieces)"
                )
            if snap.get("serve.evicted_executables"):
                lines.append(
                    f"  off-ladder executables evicted: "
                    f"{snap['serve.evicted_executables']:.0f} (LRU bound)"
                )
            if snap.get("serve.ring_dispatches"):
                # device-resident ring (docs/SERVING.md "Device-resident
                # ring"): slots/window is the dispatch-amortization factor
                lines.append(
                    "  ring windows: {:.0f} dispatches, {:.2f} slots/window "
                    "(max {:.0f}), last fill {:.0%}".format(
                        snap["serve.ring_dispatches"],
                        snap.get("serve.ring_slots_per_dispatch.mean", 0),
                        snap.get("serve.ring_slots_per_dispatch.max", 0),
                        snap.get("serve.ring_fill", 0))
                )
            if snap.get("serve.dispatches_per_wakeup.count"):
                lines.append(
                    "  dispatches/wakeup: mean {:.2f}, max {:.0f} over {:.0f} "
                    "wake-ups (> 1 = back-to-back runs engaged)".format(
                        snap["serve.dispatches_per_wakeup.mean"],
                        snap["serve.dispatches_per_wakeup.max"],
                        snap["serve.dispatches_per_wakeup.count"])
                )
            if snap.get("serve.dispatched_bytes"):
                lines.append(
                    "  dispatched cost: {:.2f} GFLOP, {:.2f} GB accessed "
                    "(achieved {:.3g} FLOP/s)".format(
                        snap.get("serve.dispatched_flops", 0) / 1e9,
                        snap["serve.dispatched_bytes"] / 1e9,
                        snap.get("serve.achieved_flops_per_s", 0))
                )
            if snap.get("serve.h2d_bytes"):
                # the quantized-serving wire instrument: exact staged bytes
                # (docs/SERVING.md "Quantized serving"); per-dispatch mean
                # quarters when serve.quant.wire=uint8
                n_disp = snap.get("serve.dispatch_seconds.count", 0)
                per = snap["serve.h2d_bytes"] / n_disp if n_disp else 0.0
                lines.append(
                    "  wire bytes (h2d): {:.3f} GB staged{}".format(
                        snap["serve.h2d_bytes"] / 1e9,
                        f", {per / 1e6:.3f} MB/dispatch" if per else "")
                )
            if snap.get("serve.int8_exports"):
                lines.append(
                    f"  int8 exports: {snap['serve.int8_exports']:.0f} "
                    "(gated post-training weight quantization)"
                )
            # the QoS/resilience edge (serve/admission.py) — per-class
            # accounting + breaker/retry/drain health, when it was in play
            classes = sorted(
                k.rsplit(".", 1)[-1] for k in snap
                if k.startswith("serve.requests.") and not k.endswith((".count", ".sum", ".mean", ".max"))
            )
            for cls in classes:
                lat = f"serve.latency_seconds.{cls}"
                row = (
                    f"  [{cls}] admitted = {snap.get(f'serve.requests.{cls}', 0):.0f}, "
                    f"completed = {snap.get(f'serve.completed.{cls}', 0):.0f}, "
                    f"rejected = {snap.get(f'serve.rejected.{cls}', 0):.0f}"
                )
                if snap.get(f"{lat}.count"):
                    row += (f", latency p50 {snap.get(f'{lat}.p50', 0) * 1e3:.2f} / "
                            f"p99 {snap.get(f'{lat}.p99', 0) * 1e3:.2f} ms "
                            f"(min {snap.get(f'{lat}.min', 0) * 1e3:.2f}, "
                            f"max {snap[f'{lat}.max'] * 1e3:.2f})")
                lines.append(row)
            if classes or snap.get("serve.breaker_opens") or snap.get("serve.retries"):
                breaker = {0: "closed", 1: "OPEN", 2: "half-open"}.get(
                    int(snap.get("serve.breaker_state", 0)), "?")
                lines.append(
                    f"  resilience: breaker {breaker} "
                    f"(opened {snap.get('serve.breaker_opens', 0):.0f}x), "
                    f"retries = {snap.get('serve.retries', 0):.0f}, "
                    f"engine failures = {snap.get('serve.engine_failures', 0):.0f}, "
                    f"drain timeouts = {snap.get('serve.drain_timeouts', 0):.0f}, "
                    f"thread crashes = {snap.get('serve.thread_crashes', 0):.0f}"
                )
            hits = {k.rsplit(".", 1)[-1]: v for k, v in snap.items() if k.startswith("serve.bucket_hits.")}
            if hits:
                lines.append("  bucket hits: " + ", ".join(f"{b}: {v:.0f}" for b, v in sorted(hits.items(), key=lambda kv: int(kv[0]))))
            if snap.get("serve.brownout_transitions") or snap.get("serve.brownout_level"):
                # the degradation ladder (serve/brownout.py): where it sits
                # now and how much it moved — recovery to L0 with up == down
                # transition counts is the healthy end state of a storm
                lines.append(
                    f"  brownout: level = L{snap.get('serve.brownout_level', 0):.0f}, "
                    f"transitions = {snap.get('serve.brownout_transitions', 0):.0f} "
                    f"(up {snap.get('serve.brownout_transitions.up', 0):.0f}, "
                    f"down {snap.get('serve.brownout_transitions.down', 0):.0f}), "
                    f"shed at door = {snap.get('serve.rejected_brownout', 0):.0f}, "
                    f"hedges suppressed = {snap.get('serve.hedges_suppressed', 0):.0f}"
                )
            if snap.get("fleet.routed") or snap.get("fleet.spawns"):
                # the replica-fleet tier (serve/router.py + cli/fleet.py):
                # routing, hedging, supervision, and scaling accounting
                lines.append(
                    f"  fleet: routed = {snap.get('fleet.routed', 0):.0f} "
                    f"(retries {snap.get('fleet.route_retries', 0):.0f}, "
                    f"errors {snap.get('fleet.route_errors', 0):.0f}, "
                    f"backpressure {snap.get('fleet.backpressure', 0):.0f}), "
                    f"replicas routable = {snap.get('fleet.replicas_routable', 0):.0f}"
                    f"/{snap.get('fleet.replicas', 0):.0f}, "
                    f"ejections = {snap.get('fleet.ejections', 0):.0f} "
                    f"(slow {snap.get('fleet.slow_ejections', 0):.0f}), "
                    f"readmissions = {snap.get('fleet.readmissions', 0):.0f}, "
                    f"restarts detected = {snap.get('fleet.replica_restarts', 0):.0f}"
                )
                lines.append(
                    f"  fleet lifecycle: spawns = {snap.get('fleet.spawns', 0):.0f} "
                    f"(failed {snap.get('fleet.spawn_failures', 0):.0f}), "
                    f"restarts = {snap.get('fleet.restarts', 0):.0f}, "
                    f"rolling restarts = {snap.get('fleet.rolling_restarts', 0):.0f}, "
                    f"chaos kills = {snap.get('fleet.chaos_kills', 0):.0f} "
                    f"(degrades {snap.get('fleet.chaos_degrades', 0):.0f}), "
                    f"scale ups/downs = {snap.get('fleet.scale_ups', 0):.0f}"
                    f"/{snap.get('fleet.scale_downs', 0):.0f}"
                )
            if (snap.get("fleet.partition_ejections") or snap.get("serve.client.connect_timeouts")
                    or snap.get("serve.netchaos.connections")):
                # partition containment (serve/netchaos.py + the connect/
                # read split): transport-shaped ejections vs crash-shaped,
                # handshake timeouts, and injected socket chaos accounting
                lines.append(
                    f"  partitions: partition ejections = "
                    f"{snap.get('fleet.partition_ejections', 0):.0f}, "
                    f"client connect timeouts = "
                    f"{snap.get('serve.client.connect_timeouts', 0):.0f}, "
                    f"netchaos conns = {snap.get('serve.netchaos.connections', 0):.0f} "
                    f"(blackholed {snap.get('serve.netchaos.blackholed', 0):.0f}, "
                    f"resets {snap.get('serve.netchaos.resets', 0):.0f}, "
                    f"half-open {snap.get('serve.netchaos.half_open', 0):.0f}, "
                    f"chaos partitions {snap.get('fleet.chaos_partitions', 0):.0f})"
                )
            if snap.get("fleet.registrations") or snap.get("fleet.lease_expirations"):
                # TTL-leased membership (the multi-host registration path):
                # joins, heartbeat renewals, and leases that lapsed — a
                # nonzero expiration count is a replica that VANISHED
                lines.append(
                    f"  fleet membership: registrations = "
                    f"{snap.get('fleet.registrations', 0):.0f} "
                    f"(renewals {snap.get('fleet.lease_renewals', 0):.0f}, "
                    f"deregistrations {snap.get('fleet.deregistrations', 0):.0f}), "
                    f"lease expirations = {snap.get('fleet.lease_expirations', 0):.0f}, "
                    f"replica heartbeats = {snap.get('serve.register_heartbeats', 0):.0f} "
                    f"(failed {snap.get('serve.register_failures', 0):.0f})"
                )
            if snap.get("serve.hedges"):
                wins = snap.get("serve.hedge_wins", 0)
                lines.append(
                    f"  hedging: fired = {snap['serve.hedges']:.0f}, "
                    f"wins = {wins:.0f} "
                    f"({100.0 * wins / snap['serve.hedges']:.0f}%), "
                    f"losers dropped = {snap.get('serve.hedge_wasted', 0):.0f}"
                )
        if snap.get("obs.compiles"):
            # device telemetry (obs/device.py, docs/OBSERVABILITY.md "Device
            # telemetry"): compile events, per-executable cost accounting,
            # dispatch efficiency, memory gauges
            lines.append("\n## device (compile / cost / memory)")
            lines.append(
                f"  compiles = {snap['obs.compiles']:.0f}, compile time "
                f"p50 {snap.get('obs.compile_seconds.p50', 0):.2f}s / "
                f"max {snap.get('obs.compile_seconds.max', 0):.2f}s "
                f"(sum {snap.get('obs.compile_seconds.sum', 0):.1f}s)"
            )
            for k in sorted(snap):
                if k.startswith("obs.cost_flops."):
                    key = k[len("obs.cost_flops."):]
                    lines.append(
                        f"  [{key}] {snap[k] / 1e9:.3f} GFLOP, "
                        f"{snap.get(f'obs.cost_bytes.{key}', 0) / 1e6:.1f} MB accessed"
                    )
            if snap.get("serve.achieved_flops_per_s"):
                lines.append(
                    f"  dispatch efficiency: {snap['serve.achieved_flops_per_s'] / 1e9:.2f} "
                    f"achieved GFLOP/s (cost FLOPs / measured serve.run_seconds)"
                )
            mem = []
            if snap.get("host.rss_bytes"):
                mem.append(f"host rss {snap['host.rss_bytes'] / 1e6:.0f} MB")
            if "device.live_buffer_bytes" in snap:
                mem.append(f"live device buffers {snap['device.live_buffer_bytes'] / 1e6:.1f} MB")
            for k in sorted(snap):
                if k.startswith("device.bytes_in_use."):
                    d = k.rsplit(".", 1)[-1]
                    peak = snap.get(f"device.peak_bytes_in_use.{d}", 0)
                    mem.append(f"{d} in-use {snap[k] / 1e6:.0f} MB (peak {peak / 1e6:.0f})")
            if mem:
                lines.append("  memory: " + ", ".join(mem))
    else:
        lines.append("\n## registry snapshot: missing (run predates obs/ or crashed before flush)")

    hang_path = os.path.join(log_dir, "hang_report.json")
    if os.path.exists(hang_path):
        with open(hang_path) as f:
            hang = json.load(f)
        lines.append(
            f"\n## !! HANG REPORT !! (stalled {hang.get('seconds_since_last_beat', 0):.1f}s, "
            f"deadline {hang.get('deadline_s', 0):.1f}s)"
        )
        lines.append(f"  last step {hang.get('last_step')} in phase '{hang.get('last_phase')}'")
        for span in hang.get("open_spans", []):
            lines.append(f"  open span: {span.get('name')} [{span.get('cat')}] "
                         f"open {span.get('open_for_s', 0):.1f}s")
        lines.append(f"  thread stacks: {len(hang.get('threads', {}))} (see {hang_path})")

    trace_path = os.path.join(log_dir, "obs_trace.json")
    if os.path.exists(trace_path):
        with open(trace_path) as f:
            n_events = len(json.load(f).get("traceEvents", []))
        lines.append(f"\n## span trace: {trace_path} ({n_events} events) — "
                     "open in ui.perfetto.dev or chrome://tracing")

    if requests:
        lines.append("\n## per-phase quantiles (registry histograms)")
        snap = {}
        if os.path.exists(reg_path):
            with open(reg_path) as f:
                snap = json.load(f)
        phase_names = [
            ("serve.queue_wait_seconds", "queue wait"),
            ("serve.dispatch_seconds", "stage+dispatch"),
            ("serve.dispatch_to_complete_seconds", "dispatch->complete"),
            ("serve.run_seconds", "run (predict->logits)"),
        ] + [
            (k[: -len(".count")], f"latency [{k.split('.')[-2]}]")
            for k in sorted(snap)
            if k.startswith("serve.latency_seconds.") and k.endswith(".count")
        ]
        table = _quantile_table(snap, phase_names)
        lines.extend(table if table else ["  no serving histograms in the registry snapshot"])
        lines.append("\n## request waterfalls (trace async events)")
        if os.path.exists(trace_path):
            lines.extend(_request_waterfalls(trace_path, max_requests))
        else:
            lines.append("  obs_trace.json missing (run with obs.trace=true)")

    if fleet:
        lines.extend(_fleet_section(log_dir))

    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("log_dir", help="a run's train.log_dir")
    ap.add_argument("--requests", action="store_true",
                    help="render per-request waterfalls + per-phase quantile tables")
    ap.add_argument("--fleet", action="store_true",
                    help="render the fleet view (merged trace, incident artifacts)")
    ap.add_argument("--max-requests", type=int, default=20,
                    help="waterfall rows to print (oldest ids first)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.log_dir):
        print(f"obs_report: not a directory: {args.log_dir}", file=sys.stderr)
        return 2
    print(summarize(args.log_dir, requests=args.requests,
                    max_requests=args.max_requests, fleet=args.fleet))
    return 0


if __name__ == "__main__":
    sys.exit(main())
