#!/usr/bin/env python
"""Render a run's telemetry (metrics.jsonl + obs_registry.json +
hang_report.json if present) into a text summary — the post-run half of
docs/OBSERVABILITY.md. Pure stdlib file reading, no jax/tf import, so it
runs anywhere (CI after the tier-1 gate, a laptop against rsynced logs).

Usage: python scripts/obs_report.py <log_dir>
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_jsonl(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def summarize(log_dir: str) -> str:
    lines = [f"# obs report: {log_dir}"]

    metrics_path = os.path.join(log_dir, "metrics.jsonl")
    if os.path.exists(metrics_path):
        rows = _load_jsonl(metrics_path)
        if rows:
            lines.append(f"\n## metrics.jsonl ({len(rows)} rows, "
                         f"steps {rows[0].get('step', '?')}..{rows[-1].get('step', '?')})")
            train_rows = [r for r in rows if any(k.startswith("train/") for k in r)]
            eval_rows = [r for r in rows if any(k.startswith("eval/") for k in r)]
            if train_rows:
                last = train_rows[-1]
                for key in ("train/loss", "train/images_per_sec", "train/images_per_sec_per_chip"):
                    if key in last:
                        lines.append(f"  last {key} = {last[key]:.6g} (step {last['step']})")
            if eval_rows:
                best = max(eval_rows, key=lambda r: r.get("eval/top1", float("-inf")))
                if "eval/top1" in best:
                    lines.append(f"  best eval/top1 = {best['eval/top1']:.6g} (step {best['step']})")
                last = eval_rows[-1]
                for key in ("eval/top1", "eval/loss"):
                    if key in last:
                        lines.append(f"  last {key} = {last[key]:.6g} (step {last['step']})")
        else:
            lines.append("\n## metrics.jsonl: empty")
    else:
        lines.append("\n## metrics.jsonl: missing")

    reg_path = os.path.join(log_dir, "obs_registry.json")
    if os.path.exists(reg_path):
        with open(reg_path) as f:
            snap = json.load(f)
        lines.append(f"\n## registry snapshot ({len(snap)} metrics)")
        for name in sorted(snap):
            lines.append(f"  {name} = {snap[name]:.6g}")
        if any(k.startswith("serve.") for k in snap):
            # serving run (docs/SERVING.md): derive the headline numbers from
            # the histograms the engine/batcher populate
            lines.append("\n## serving")
            lines.append(
                "  requests = {:.0f}, completed = {:.0f}, shed = {:.0f}, "
                "rejected = {:.0f}".format(
                    snap.get("serve.requests", 0), snap.get("serve.completed", 0),
                    snap.get("serve.shed_deadline", 0), snap.get("serve.rejected_full", 0))
            )
            for h, label in (("serve.queue_wait_seconds", "queue wait"),
                             ("serve.run_seconds", "run latency"),
                             ("serve.dispatch_seconds", "dispatch"),
                             ("serve.dispatch_to_complete_seconds", "dispatch->complete")):
                if snap.get(f"{h}.count"):
                    lines.append(
                        f"  {label}: mean {snap[f'{h}.mean'] * 1e3:.2f} ms, "
                        f"max {snap[f'{h}.max'] * 1e3:.2f} ms over {snap[f'{h}.count']:.0f}"
                    )
            if snap.get("serve.batch_size.count"):
                lines.append(
                    f"  batch size: mean {snap['serve.batch_size.mean']:.2f}, "
                    f"max {snap['serve.batch_size.max']:.0f}"
                )
            if snap.get("serve.shed_at_completion"):
                lines.append(
                    f"  shed at completion: {snap['serve.shed_at_completion']:.0f} "
                    "(deadline passed while the batch executed)"
                )
            if snap.get("serve.fused_dispatches"):
                lines.append(
                    f"  fused dispatches: {snap['serve.fused_dispatches']:.0f} "
                    f"covering {snap.get('serve.fused_chunks', 0):.0f} chunks "
                    "(whole-request lax.scan pieces)"
                )
            if snap.get("serve.evicted_executables"):
                lines.append(
                    f"  off-ladder executables evicted: "
                    f"{snap['serve.evicted_executables']:.0f} (LRU bound)"
                )
            # the QoS/resilience edge (serve/admission.py) — per-class
            # accounting + breaker/retry/drain health, when it was in play
            classes = sorted(
                k.rsplit(".", 1)[-1] for k in snap
                if k.startswith("serve.requests.") and not k.endswith((".count", ".sum", ".mean", ".max"))
            )
            for cls in classes:
                lat = f"serve.latency_seconds.{cls}"
                row = (
                    f"  [{cls}] admitted = {snap.get(f'serve.requests.{cls}', 0):.0f}, "
                    f"completed = {snap.get(f'serve.completed.{cls}', 0):.0f}, "
                    f"rejected = {snap.get(f'serve.rejected.{cls}', 0):.0f}"
                )
                if snap.get(f"{lat}.count"):
                    row += (f", latency mean {snap[f'{lat}.mean'] * 1e3:.2f} ms "
                            f"max {snap[f'{lat}.max'] * 1e3:.2f} ms")
                lines.append(row)
            if classes or snap.get("serve.breaker_opens") or snap.get("serve.retries"):
                breaker = {0: "closed", 1: "OPEN", 2: "half-open"}.get(
                    int(snap.get("serve.breaker_state", 0)), "?")
                lines.append(
                    f"  resilience: breaker {breaker} "
                    f"(opened {snap.get('serve.breaker_opens', 0):.0f}x), "
                    f"retries = {snap.get('serve.retries', 0):.0f}, "
                    f"engine failures = {snap.get('serve.engine_failures', 0):.0f}, "
                    f"drain timeouts = {snap.get('serve.drain_timeouts', 0):.0f}, "
                    f"thread crashes = {snap.get('serve.thread_crashes', 0):.0f}"
                )
            hits = {k.rsplit(".", 1)[-1]: v for k, v in snap.items() if k.startswith("serve.bucket_hits.")}
            if hits:
                lines.append("  bucket hits: " + ", ".join(f"{b}: {v:.0f}" for b, v in sorted(hits.items(), key=lambda kv: int(kv[0]))))
    else:
        lines.append("\n## registry snapshot: missing (run predates obs/ or crashed before flush)")

    hang_path = os.path.join(log_dir, "hang_report.json")
    if os.path.exists(hang_path):
        with open(hang_path) as f:
            hang = json.load(f)
        lines.append(
            f"\n## !! HANG REPORT !! (stalled {hang.get('seconds_since_last_beat', 0):.1f}s, "
            f"deadline {hang.get('deadline_s', 0):.1f}s)"
        )
        lines.append(f"  last step {hang.get('last_step')} in phase '{hang.get('last_phase')}'")
        for span in hang.get("open_spans", []):
            lines.append(f"  open span: {span.get('name')} [{span.get('cat')}] "
                         f"open {span.get('open_for_s', 0):.1f}s")
        lines.append(f"  thread stacks: {len(hang.get('threads', {}))} (see {hang_path})")

    trace_path = os.path.join(log_dir, "obs_trace.json")
    if os.path.exists(trace_path):
        with open(trace_path) as f:
            n_events = len(json.load(f).get("traceEvents", []))
        lines.append(f"\n## span trace: {trace_path} ({n_events} events) — "
                     "open in ui.perfetto.dev or chrome://tracing")

    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("log_dir", help="a run's train.log_dir")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.log_dir):
        print(f"obs_report: not a directory: {args.log_dir}", file=sys.stderr)
        return 2
    print(summarize(args.log_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
