#!/usr/bin/env python
"""Serving benchmark: folded-model inference latency/QPS per batch bucket.

Prints exactly ONE JSON line on stdout in the bench.py artifact shape
(tests/test_bench_contract.py contract: exit 0 always; a failed run emits
``value: null`` with an ``error`` field, never a stack trace) and optionally
writes it to a BENCH_SERVE_*.json via --out:

  {"metric": "<arch>_serve_images_per_sec", "value": <peak qps>,
   "unit": "images/sec", "vs_baseline": null, "platform": ...,
   "buckets": [{"batch": B, "p50_ms": ..., "p99_ms": ..., "qps": ...}, ...]}

The model is random-init + synthetic BN stats, folded through the real
serve/export transform and dispatched through the real AOT engine — the
numbers measure the serving path (compile, pad, dispatch, device_get), which
does not depend on trained weight values.

Usage: python scripts/serve_bench.py [--arch mobilenet_v3_large]
           [--image-size 224] [--buckets 1,8,32] [--iters 20] [--out f.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def measure(arch: str, image_size: int, buckets: tuple[int, ...], iters: int) -> dict:
    import jax
    import numpy as np

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
    from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle, fold_network

    if arch == "tiny":  # contract-test preset: 2 blocks, compiles in seconds
        mc = ModelConfig(arch="mobilenet_v2", num_classes=16, dropout=0.0,
                         block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}, {"t": 2, "c": 16, "n": 1, "s": 2}])
    else:
        mc = ModelConfig(arch=arch)
    net = get_model(mc, image_size)
    params, state = net.init(jax.random.PRNGKey(0))
    bundle = InferenceBundle(net=net, params=fold_network(net, params, state), meta={})
    engine = InferenceEngine(bundle, buckets=buckets, image_size=image_size)

    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0

    rng = np.random.RandomState(0)
    rows = []
    for b in engine.buckets:
        x = rng.normal(0, 1, (b, image_size, image_size, 3)).astype(np.float32)
        engine.predict(x)  # one untimed call: page in the executable
        lat = []
        for _ in range(iters):
            t1 = time.perf_counter()
            engine.predict(x)
            lat.append(time.perf_counter() - t1)
        lat.sort()
        mean = sum(lat) / len(lat)
        rows.append({
            "batch": b,
            "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
            "qps": round(b / mean, 2),
        })
    dev = jax.devices()[0]
    return {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "n_chips": len(jax.devices()),
        "warmup_compile_s": round(warmup_s, 2),
        "buckets": rows,
        "peak_qps": max(r["qps"] for r in rows),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default="mobilenet_v3_large")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--buckets", default="1,8,32")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out", default="", help="also write the JSON artifact here")
    args = ap.parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    out = {
        "metric": f"{args.arch}_serve_images_per_sec",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "vs_baseline_note": "no serving reference measurement exists yet",
        "image_size": args.image_size,
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        m = measure(args.arch, args.image_size, buckets, max(1, args.iters))
        out.update(m)
        out["value"] = m["peak_qps"]
    except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
        out["error"] = f"{type(e).__name__}: {e}"
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
