#!/usr/bin/env python
"""Serving benchmark: latency/QPS per bucket + pipelined/bf16/chaos A/Bs.

Prints exactly ONE JSON line on stdout in the bench.py artifact shape
(tests/test_bench_contract.py contract: exit 0 always; a failed run emits
``value: null`` with an ``error`` field, never a stack trace) and optionally
writes it to a BENCH_SERVE_*.json via --out. Four measurements per run:

1. **direct** — engine.predict latency per (bucket, image_size), exact-bucket
   batches: p50/p99 ms + QPS (the BENCH_SERVE_r01 shape, now per size).
2. **concurrent-submit A/B** — closed-loop client threads submitting single
   images through the real batcher, once through the legacy sync
   MicroBatcher and once through the PipelinedBatcher (serve/pipeline.py):
   per-(bucket, size) ``qps_sync`` vs ``qps_pipelined``. This measures the
   tentpole: continuous batching + async double-buffered dispatch hiding
   host collect/stage time behind device compute.
3. **fp32-vs-bf16 A/B** — a second engine with compute_dtype=bfloat16,
   direct QPS per bucket plus the measured max |logit delta| vs fp32
   against the pinned BF16_PARITY_ATOL (serve/engine.py).
4. **chained-vs-fused A/B** (``--fused``) — the serving twin of the training
   dispatch probe (PROFILE.md): whole requests of K max-bucket chunks served
   once through the per-chunk path (K dispatches, host staging between each)
   and once through the fused multi-chunk executables (serve/engine.py
   ``fuse_ladder``: ONE ``lax.scan`` dispatch per ladder piece). Per K:
   dispatches/request (the structural claim — 1 for on-ladder K), p50/p99,
   QPS, speedup, and the bitwise-parity check; plus the CPU-rehearsal caveat
   recorded in the artifact (on 1 core the dispatch boundary is nearly free,
   so the speedup may be ~flat — the dispatch-count drop is the pinned win).
5. **structural sweep** (``--structural``) — ONE interleaved sweep across
   the five serving structures at a saturated bucket: **sync** (blocking
   collect->predict cycle), **pipelined** (async in-flight window),
   **fused** (coalesced overflow rides the lax.scan executables),
   **overlapped** (fence-tracked slot staging with async H2D + back-to-back
   runs: > 1 dispatch per completion wake-up, serve/pipeline.py), and
   **ring** (device-resident request ring, serve/ring.py: a window of up
   to R staged max-bucket slots consumed by ONE masked-scan dispatch).
   Rounds interleave mode-by-mode so box drift hits all five alike; per
   mode the row carries median QPS, fill, dispatches/request, the
   ``serve.dispatches_per_wakeup`` registry delta (the back-to-back
   structural claim — None for sync, 1.0 for per-batch pipelining; a ring
   window is ONE piece, so the per-batch [1, 2] bound does not apply), the
   steady-state ``serve.achieved_flops_per_s`` window (dispatched cost
   FLOPs ÷ measured run seconds) next to the single-dispatch reference,
   ring window counts, and registry-math latency quantiles. The sweep also
   pins the deterministic ``ring_probe``: a saturated R-slot window is
   exactly ONE ``serve.dispatch_seconds`` observation, bitwise vs the
   per-batch path. Emits the BENCH_SERVE_r12 shape (r05 + the ring arm).
6. **chaos A/B** — an OPEN-LOOP Poisson load generator (arrivals fire on
   schedule regardless of completions — closed loops hide overload) drives
   mixed priorities (interactive/batch/best_effort via serve/admission.py)
   and mixed image sizes through the pipelined batcher twice: a healthy
   round and a faulty round (serve/faults.py: seeded failure rate + latency
   spikes at the completion edge). Per class: submitted / completed /
   rejected / shed / failed / p50 / p99, plus retry, injected-fault,
   rejection-cause, and breaker accounting from the obs registry deltas —
   and the invariant that EVERY request resolved (``unresolved`` must be
   0). Both rounds share one arrival schedule (same seed), so the delta is
   the injected faults, not the load draw.

8. **quantized-serving A/B** (``--quant``) — ONE interleaved sweep over the
   three serving precisions per bucket: **f32** (the status quo),
   **uint8-wire** (raw pixels on the wire, device denorm), and **int8**
   (uint8 wire + post-training int8 weights). Per mode: median-of-rounds
   QPS/p50/p99 plus the byte instruments from registry math — per-request
   ``serve.h2d_bytes`` (the uint8 wire moves EXACTLY 1/4 of the f32 bytes,
   on any host) and ``serve.dispatched_bytes`` — and the parity verdicts:
   zero-mean denorm bitwise, mean/std wire delta vs the configured atol,
   and the int8 export's gated top-1 agreement. Emits the BENCH_SERVE_r07
   shape.

The model is random-init + synthetic BN stats, folded through the real
serve/export transform and dispatched through the real AOT engine — the
numbers measure the serving path (compile, pad, dispatch, device_get), which
does not depend on trained weight values.

7. **replica fleet** (``--fleet``, standalone mode) — a REAL fleet of N
   ``cli/serve.py`` replica subprocesses behind the router tier
   (serve/router.py), measured three ways on shared seeded schedules:
   hedged-vs-unhedged tail A/B against a latency-injected straggler
   replica (``serve.hedges``/``serve.hedge_wins`` + p99 delta), a kill -9
   availability round (every submitted request must resolve as completed
   or typed-rejected, the supervisor must restart the corpse), and the
   autoscaler's N-over-time trace across a diurnal low/high/low open-loop
   schedule (cooldown respected). Emits the BENCH_SERVE_r06 shape.

10. **partition** (``--partition``, standalone mode, jax-free) — the
   multi-host partition-containment acceptance (serve/netchaos.py): N
   in-process echo replicas each behind a seeded socket-level fault proxy,
   one router over the proxy addresses. Seeded blackhole / reset /
   half-open / flap rounds inject at the SOCKET level a third of the way
   in and heal at two thirds, measuring detection time (fault onset ->
   ejection, stamped by a counter watcher), client-visible error rate
   (the contract is ZERO — transport retry absorbs every shape), and
   recovery (heal -> fully routable through the probation); then the
   TTL-lease membership round: a leased replica joins by heartbeat,
   silently vanishes (heartbeat stops + link blackholed), and must be
   REMOVED by lease expiry within TTL + one poll sweep. Emits the
   BENCH_SERVE_r09 shape.

11. **multi-model zoo** (``--zoo``, standalone mode) — the zoo/cascade
   acceptance (serve/zoo.py, serve/cascade.py): ONE 2-replica
   model-sharded fleet (slot 0 serves the int8 'small' tier, slot 1 the
   f32 'big' tier via per-slot ``serve.zoo.models`` assignments, placement
   advertised to the model-aware router) A/B'd three ways over ONE seeded
   trace: **big_only** (every request pinned ``X-Model: big`` — the
   one-model-per-fleet baseline), **sharded** (seeded 50/50 pins; the
   per-replica ``serve.model_requests.{model}`` deltas must show ZERO
   misroutes and the books zero 5xx), and **cascade** (unqualified
   submits: the small tier answers confident requests, low-margin ones
   re-submit to the big tier at the router). Pinned: escalations > 0 AND
   answered_small > 0 (the threshold calibrates to the trace's median
   margin), every cascade answer bitwise-matches exactly one of the two
   per-image explicit-pin references (escalated answers EQUAL the
   big-only arm's), and the fleet-wide dispatched-FLOPs/request mean of
   the cascade arm sits STRICTLY below the big-only arm's (the cost
   proxy: per-replica ``serve.dispatched_flops`` deltas). Emits the
   BENCH_SERVE_r11 shape.

9. **overload** (``--overload``, standalone mode) — the brownout ladder's
   acceptance experiment (serve/brownout.py): ONE seeded open-loop Poisson
   storm at ``--overload-multiple`` x the measured closed-loop capacity
   (the engine paced by a seeded per-dispatch latency floor so capacity is
   box-independent), played through fresh batcher+admission stacks twice —
   brownout OFF vs ON. Pinned: interactive availability ON > OFF, zero
   unresolved futures in both arms, the ladder stepping up during the
   storm AND fully recovering to L0 after it. Then the GRAY-FAILURE round:
   a real fleet with a latency-injected (never crashing) straggler, soft
   ejection armed mid-round — time-to-eject from the arming instant, and
   the p99 of requests submitted after the ejection vs before (the
   submit-time split makes the recovery claim routing-honest). Emits the
   BENCH_SERVE_r08 shape.

Usage: python scripts/serve_bench.py [--arch mobilenet_v3_large]
           [--image-sizes 224] [--buckets 1,8,32] [--iters 10]
           [--concurrent-iters 6] [--ab-iters 5] [--no-bf16]
           [--fused] [--fuse-ladder 2,4] [--fused-iters 8]
           [--structural] [--structural-rounds 3]
           [--quant] [--quant-iters 5] [--quant-rounds 3]
           [--quant-top1-min 0.9]
           [--chaos-requests 80] [--chaos-qps 0] [--chaos-fault-rate 0.05]
           [--no-chaos] [--out f.json]
       python scripts/serve_bench.py --fleet [--fleet-replicas 2]
           [--fleet-requests 40] [--fleet-qps 0] [--fleet-straggler-ms 400]
           [--fleet-phase-s 5,20,10] [--fleet-seed 0] [--out f.json]
       python scripts/serve_bench.py --overload [--overload-storm-s 5]
           [--overload-multiple 3] [--overload-pace-ms 20]
           [--overload-replicas 2] [--overload-gray-requests 60]
           [--overload-straggler-ms 300] [--overload-seed 0] [--out f.json]
       python scripts/serve_bench.py --partition [--partition-replicas 3]
           [--partition-requests 120] [--partition-qps 30]
           [--partition-poll-s 0.1] [--partition-connect-timeout-s 0.4]
           [--partition-read-timeout-s 2.0] [--partition-lease-ttl-s 1.5]
           [--partition-seed 0] [--out f.json]
       python scripts/serve_bench.py --zoo [--zoo-requests 48]
           [--zoo-qps 0] [--zoo-threshold -1] [--zoo-int8-top1-min 0.5]
           [--zoo-seed 0] [--out f.json]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _hist_delta_quantiles(name, counts_before):
    """p50/p95/p99 (ms) of one measured WINDOW of a registry histogram:
    bucket-count deltas against the pre-window snapshot, estimated through
    the registry's own interpolation (obs.registry.quantiles_from_counts) —
    the bench reports the same math /metrics scrapes, not its own
    percentile-of-a-list."""
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry, quantiles_from_counts

    h = get_registry().histogram(name)
    counts = [a - b for a, b in zip(h.bucket_counts(), counts_before)]
    p50, p95, p99 = quantiles_from_counts(h.bounds, counts, (0.5, 0.95, 0.99))
    return {
        "count": int(sum(counts)),
        "p50_ms": round(p50 * 1e3, 3),
        "p95_ms": round(p95 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
    }


def _hist_counts(name):
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry

    return get_registry().histogram(name).bucket_counts()


def _direct_row(engine, batch, size, iters, rng):
    """Exact-bucket engine.predict latency: one untimed page-in, then iters.
    Client-side wall p50/p99 plus the registry's own bucketed quantiles of
    the same window (serve.run_seconds deltas) ride in every row."""
    x = rng.normal(0, 1, (batch, size, size, 3)).astype("float32")
    engine.predict(x)
    run_counts0 = _hist_counts("serve.run_seconds")
    lat = []
    for _ in range(iters):
        t1 = time.perf_counter()
        engine.predict(x)
        lat.append(time.perf_counter() - t1)
    reg_q = _hist_delta_quantiles("serve.run_seconds", run_counts0)
    lat.sort()
    mean = sum(lat) / len(lat)
    return {
        "batch": batch,
        "image_size": size,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
        "p50_ms_registry": reg_q["p50_ms"],
        "p95_ms_registry": reg_q["p95_ms"],
        "p99_ms_registry": reg_q["p99_ms"],
        "qps": round(batch / mean, 2),
    }


def _drive_concurrent(batcher, image, n_requests, n_clients):
    """Closed-loop clients: each submits one image, waits, repeats. Returns
    (qps, sorted latencies). The batcher must already be started."""
    lock = threading.Lock()
    left = [n_requests]
    lat: list[float] = []

    def client():
        while True:
            with lock:
                if left[0] <= 0:
                    return
                left[0] -= 1
            t0 = time.perf_counter()
            fut = batcher.submit(image)
            fut.result(timeout=300)
            with lock:
                lat.append(time.perf_counter() - t0)

    threads = [threading.Thread(target=client, daemon=True) for _ in range(n_clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    lat.sort()
    return (len(lat) / wall if wall > 0 else 0.0), lat


def _concurrent_row(engine, batch, size, conc_iters, max_inflight, rng):
    """Sync-vs-pipelined QPS through the real batchers at max_batch=batch.

    2*batch closed-loop clients drive both batchers (sharing one warm
    engine) in INTERLEAVED rounds — sync, pipelined, sync, pipelined... —
    and the reported QPS is the per-mode MEDIAN of 5 rounds: on a shared
    box, minute-scale CPU drift is bigger than the effect under test;
    interleaving makes drift hit both modes alike, and the median (unlike
    best-of or mean) ignores the occasional round that lands in a lucky or
    throttled scheduler window. Per-round arrays are recorded in the
    artifact so the spread is visible. The request count per round is
    floored (a 12-request window is pure scheduler noise) and capped (the
    biggest bucket would otherwise dominate the whole run).
    ``avg_fill_*`` (serve.batch_size histogram deltas) says how full the
    dispatched buckets actually were — fill < 1 means padded dead rows, a
    batching-policy failure the QPS numbers would otherwise hide."""
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve.batcher import MicroBatcher
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    image = rng.normal(0, 1, (size, size, 3)).astype("float32")
    n_clients = min(max(2 * batch, 4), 64)
    n_requests = min(max(conc_iters * batch, 48), 96)
    rounds = 5
    # a long linger fills buckets; the pipelined path hides it behind compute
    common = dict(max_batch=batch, max_wait_ms=10.0, queue_depth=max(64, 4 * batch))
    reg = get_registry()
    row = {"batch": batch, "image_size": size, "requests": n_requests, "clients": n_clients,
           "rounds": rounds}
    batchers = {
        "sync": MicroBatcher(engine.predict, **common).start(),
        "pipelined": PipelinedBatcher(engine, max_inflight=max_inflight, **common).start(),
    }
    runs = {m: [] for m in batchers}  # (qps, lat) per round
    fills = {m: [] for m in batchers}
    try:
        for b in batchers.values():  # warm both paths
            _drive_concurrent(b, image, min(2 * batch, n_requests), n_clients)
        for _ in range(rounds):
            for mode, b in batchers.items():
                s0 = reg.snapshot()
                qps, lat = _drive_concurrent(b, image, n_requests, n_clients)
                s1 = reg.snapshot()
                d_count = s1["serve.batch_size.count"] - s0["serve.batch_size.count"]
                d_sum = s1["serve.batch_size.sum"] - s0["serve.batch_size.sum"]
                fills[mode].append(d_sum / d_count / batch if d_count else 0.0)
                runs[mode].append((qps, lat))
    finally:
        for b in batchers.values():
            b.stop()
    for mode in batchers:
        ordered = sorted(runs[mode], key=lambda r: r[0])
        med_qps, med_lat = ordered[len(ordered) // 2]
        row[f"qps_{mode}"] = round(med_qps, 2)
        row[f"qps_rounds_{mode}"] = [round(q, 2) for q, _ in runs[mode]]
        row[f"p99_ms_{mode}"] = round(_percentile(med_lat, 0.99) * 1e3, 3)
        row[f"avg_fill_{mode}"] = round(sum(fills[mode]) / len(fills[mode]), 3)
    row["pipelined_speedup"] = round(row["qps_pipelined"] / row["qps_sync"], 4) if row["qps_sync"] else None
    return row


# recorded in every fused A/B artifact, the way r02 recorded the pipelined
# caveat: the structural claim a 1-core box CAN pin is the dispatch count
_FUSED_CPU_CAVEAT = (
    "cpu_rehearsal: on a 1-core host the per-dispatch boundary costs little "
    "(host staging and XLA 'device' compute share the core), so the fused "
    "speedup may be ~flat here; the pinned structural win is "
    "dispatches_per_request dropping to 1 for on-ladder K (bitwise-identical "
    "logits). The throughput claim is an accelerator measurement — ROADMAP "
    "item 1, same caveat discipline as BENCH_SERVE_r02."
)


def _fused_ab(chained, fused, size, iters, rng):
    """Chained (per-chunk) vs fused (lax.scan) whole-request serving: same
    bundle, same buckets, K max-bucket chunks per request for every K on the
    fuse ladder plus one off-ladder K (decomposes into ladder pieces). The
    dispatch count per request comes from serve.dispatch_seconds.count
    registry deltas — the structural measurement; latency/QPS ride along."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.obs.registry import get_registry

    reg = get_registry()
    cap = fused.buckets[-1]
    ladder = list(fused.fuse_ladder)
    off_k = next(k for k in range(2, max(ladder) + 2) if k not in ladder)
    rows = []
    for k in ladder + [off_k]:
        n = k * cap
        x = rng.normal(0, 1, (n, size, size, 3)).astype("float32")
        ref = chained.predict(x)
        row = {"k": k, "rows": n, "on_ladder": k in ladder,
               "bitwise_ok": bool(np.array_equal(fused.predict(x), ref))}
        for label, eng in (("chained", chained), ("fused", fused)):
            eng.predict(x)  # untimed page-in
            s0 = reg.snapshot()
            lat = []
            for _ in range(iters):
                t0 = time.perf_counter()
                eng.predict(x)
                lat.append(time.perf_counter() - t0)
            s1 = reg.snapshot()
            lat.sort()
            mean = sum(lat) / len(lat)
            row[f"p50_ms_{label}"] = round(_percentile(lat, 0.50) * 1e3, 3)
            row[f"p99_ms_{label}"] = round(_percentile(lat, 0.99) * 1e3, 3)
            row[f"qps_{label}"] = round(n / mean, 2)
            row[f"dispatches_per_request_{label}"] = round(
                (s1["serve.dispatch_seconds.count"] - s0["serve.dispatch_seconds.count"]) / iters, 3)
        row["fused_speedup"] = (
            round(row["qps_fused"] / row["qps_chained"], 4) if row["qps_chained"] else None)
        rows.append(row)
    return {
        "ladder": ladder,
        "off_ladder_k": off_k,
        "max_bucket": cap,
        "image_size": size,
        "per_k": rows,
        "peak_speedup": max(r["fused_speedup"] for r in rows),
        "cpu_rehearsal_note": _FUSED_CPU_CAVEAT,
    }


_STRUCTURAL_CPU_CAVEAT = (
    "cpu_rehearsal: host staging/collect work and XLA 'device' compute share "
    "the core(s) on this box, so overlapped staging and back-to-back dispatch "
    "cannot add throughput here (QPS columns may be ~flat or slightly "
    "negative). The pinned structural wins are dispatches_per_wakeup > 1 on "
    "the saturated bucket, bitwise-identical logits, and the dispatch/ "
    "transfer accounting; for the ring arm they are the deterministic "
    "one-dispatch window probe (a saturated R-slot window == ONE "
    "serve.dispatch_seconds observation, registry-delta counted), "
    "serve.ring_dispatches > 0 under the driven burst, and bitwise parity "
    "vs the per-batch path. The throughput claim is an accelerator "
    "measurement — ROADMAP item 2's hardware rung, same caveat discipline "
    "as r02/r04."
)


def _structural_sweep(make_engine, size, *, rounds, conc_iters, max_inflight,
                      staging_slots, run_max, fuse_ladder, rng,
                      ring_slots=4, ring_min_fill=0.5):
    """One interleaved sweep across the five serving structures on a
    saturated bucket (docs/SERVING.md "Overlapped staging" and
    "Device-resident ring"):

    - ``sync``       MicroBatcher: blocking collect -> predict -> resolve
    - ``pipelined``  PipelinedBatcher(run_max=1), chained engine
    - ``fused``      PipelinedBatcher(run_max=1), fused-scan engine
    - ``overlapped`` PipelinedBatcher(run_max), overlapped-staging fused
                     engine — the device-resident steady state
    - ``ring``       PipelinedBatcher over a ring-mode overlapped engine:
                     saturated windows of up to ``ring_slots`` staged
                     max-bucket slots consumed by ONE masked-scan dispatch

    All share ``max_batch = 2 * max_bucket`` so every saturated coalesced
    group exceeds the biggest bucket (the fused/overlapped modes serve it
    as ONE engine call). Rounds interleave mode-by-mode so box drift hits
    all four alike; median-of-rounds QPS like the r02 A/B. Per mode the
    row also carries the registry-delta instruments the structural claims
    are read from: dispatches/request, dispatches-per-wakeup (None for
    sync — the MicroBatcher has no completion thread), steady-state
    achieved FLOPs/s, and the same window's bucketed latency quantiles."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.obs import device as obs_device
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve.batcher import MicroBatcher
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    reg = get_registry()
    eng_chained = make_engine("float32")
    eng_fused = make_engine("float32", fuse=fuse_ladder)
    eng_overlap = make_engine("float32", fuse=fuse_ladder, overlap=True,
                              staging_slots=staging_slots)
    eng_ring = make_engine("float32", overlap=True, staging_slots=staging_slots,
                           ring_slots=ring_slots)
    for e in (eng_chained, eng_fused, eng_overlap, eng_ring):
        e.warmup()
    cap = eng_chained.buckets[-1]
    max_batch = 2 * cap
    # saturation by construction: with the window holding 2 full batches in
    # flight, 3 x max_batch closed-loop clients keep >= max_batch requests
    # queued — the back-to-back condition — for the whole round
    n_clients = 3 * max_batch
    n_requests = min(max(conc_iters * max_batch, 2 * n_clients), 384)
    image = rng.normal(0, 1, (size, size, 3)).astype("float32")
    # bitwise parity across the whole structural ladder, one oversized batch
    xp = rng.normal(0, 1, (max_batch, size, size, 3)).astype("float32")
    ref = eng_chained.predict(xp)
    bitwise_ok = bool(
        np.array_equal(eng_fused.predict(xp), ref)
        and np.array_equal(eng_overlap.predict(xp), ref)
        and np.array_equal(eng_ring.predict(xp), ref)  # per-batch fallback path
    )
    # the ring's headline, pinned deterministically before the driven rounds:
    # a saturated window of R full max-bucket slots is exactly ONE
    # serve.dispatch_seconds observation (registry-delta counted), fill 1.0,
    # and its drained logits are bitwise-identical to the per-batch path
    xr = rng.normal(0, 1, (ring_slots * cap, size, size, 3)).astype("float32")
    ring_ref = np.concatenate(
        [eng_chained.predict(np.ascontiguousarray(xr[i * cap:(i + 1) * cap]))
         for i in range(ring_slots)])
    s0 = reg.snapshot()
    entries = [eng_ring.ring_stage(np.ascontiguousarray(xr[i * cap:(i + 1) * cap]))
               for i in range(ring_slots)]
    ring_out = eng_ring.ring_dispatch(entries).result()
    s1 = reg.snapshot()
    ring_probe = {
        "slots": ring_slots,
        "rows": int(ring_slots * cap),
        "dispatch_seconds_count_delta": int(
            s1.get("serve.dispatch_seconds.count", 0)
            - s0.get("serve.dispatch_seconds.count", 0)),
        "ring_dispatches_delta": int(
            s1.get("serve.ring_dispatches", 0) - s0.get("serve.ring_dispatches", 0)),
        "fill": float(s1.get("serve.ring_fill", 0.0)),
        "bitwise_ok": bool(np.array_equal(ring_out, ring_ref)),
    }
    # single-dispatch reference for the efficiency column: cost FLOPs of the
    # full max bucket over its measured direct latency (one warm predict)
    xb = rng.normal(0, 1, (cap, size, size, 3)).astype("float32")
    eng_chained.predict(xb)
    lat = []
    for _ in range(5):
        t0 = time.perf_counter()
        eng_chained.predict(xb)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    flops_1 = obs_device.flops_for(f"serve_b{cap}_s{size}_k1")
    single_dispatch_ref = flops_1 / _percentile(lat, 0.5) if lat[0] > 0 else 0.0

    common = dict(max_batch=max_batch, max_wait_ms=10.0, queue_depth=max(256, 8 * max_batch))
    batchers = {
        "sync": MicroBatcher(eng_chained.predict, **common).start(),
        "pipelined": PipelinedBatcher(eng_chained, max_inflight=max_inflight, **common).start(),
        "fused": PipelinedBatcher(eng_fused, max_inflight=max_inflight, **common).start(),
        "overlapped": PipelinedBatcher(
            eng_overlap, max_inflight=max_inflight, run_max=run_max, **common
        ).start(),
        "ring": PipelinedBatcher(
            eng_ring, max_inflight=max_inflight, run_max=run_max,
            ring_min_fill=ring_min_fill, **common
        ).start(),
    }
    runs = {m: [] for m in batchers}  # per round: (qps, lat, deltas dict)
    try:
        for b in batchers.values():  # warm every path off the measured window
            _drive_concurrent(b, image, min(2 * max_batch, n_requests), n_clients)
        for _ in range(rounds):
            for mode, b in batchers.items():
                run_counts0 = _hist_counts("serve.run_seconds")
                s0 = reg.snapshot()
                qps, lat = _drive_concurrent(b, image, n_requests, n_clients)
                s1 = reg.snapshot()
                d = {k: s1.get(k, 0) - s0.get(k, 0) for k in (
                    "serve.dispatch_seconds.count", "serve.batch_size.count",
                    "serve.batch_size.sum", "serve.dispatches_per_wakeup.count",
                    "serve.dispatches_per_wakeup.sum", "serve.dispatched_flops",
                    "serve.dispatched_bytes", "serve.run_seconds.sum",
                    "serve.ring_dispatches", "serve.ring_slots_per_dispatch.count",
                    "serve.ring_slots_per_dispatch.sum",
                )}
                d["registry_q"] = _hist_delta_quantiles("serve.run_seconds", run_counts0)
                runs[mode].append((qps, lat, d))
    finally:
        for b in batchers.values():
            b.stop()
    modes = {}
    for mode, rows in runs.items():
        ordered = sorted(rows, key=lambda r: r[0])
        med_qps, med_lat, _ = ordered[len(ordered) // 2]
        # instruments sum over ALL rounds: the steady-state windows, not one
        # lucky round, back the structural claims
        tot = {k: sum(r[2][k] for r in rows) for k in rows[0][2] if k != "registry_q"}
        reg_q = ordered[len(ordered) // 2][2]["registry_q"]
        dispatches = tot["serve.dispatch_seconds.count"]
        batches = tot["serve.batch_size.count"]
        wakeups = tot["serve.dispatches_per_wakeup.count"]
        modes[mode] = {
            "qps": round(med_qps, 2),
            "qps_rounds": [round(q, 2) for q, _, _ in rows],
            "p99_ms": round(_percentile(med_lat, 0.99) * 1e3, 3),
            "p50_ms_registry": reg_q["p50_ms"],
            "p99_ms_registry": reg_q["p99_ms"],
            "avg_fill": round(tot["serve.batch_size.sum"] / batches / max_batch, 3) if batches else 0.0,
            "dispatches_per_request": round(dispatches / (rounds * n_requests), 4),
            # None for sync: the MicroBatcher has no completion wake-ups
            "dispatches_per_wakeup": (
                round(tot["serve.dispatches_per_wakeup.sum"] / wakeups, 4) if wakeups else None
            ),
            "dispatched_gflops": round(tot["serve.dispatched_flops"] / 1e9, 3),
            "dispatched_gbytes": round(tot["serve.dispatched_bytes"] / 1e9, 3),
            # the steady-state dispatch-efficiency window (the same math the
            # serve.achieved_flops_per_s pull gauge exposes, but delta-scoped
            # to this mode's rounds)
            "achieved_flops_per_s": round(
                tot["serve.dispatched_flops"] / tot["serve.run_seconds.sum"], 1
            ) if tot["serve.run_seconds.sum"] > 0 else 0.0,
            # ring instruments: windows consumed + average staged slots per
            # window (identically 0/None for the four per-batch arms)
            "ring_windows": int(tot["serve.ring_dispatches"]),
            "ring_slots_per_window": (
                round(tot["serve.ring_slots_per_dispatch.sum"]
                      / tot["serve.ring_slots_per_dispatch.count"], 3)
                if tot["serve.ring_slots_per_dispatch.count"] else None
            ),
        }
    return {
        "image_size": size,
        "max_bucket": cap,
        "max_batch": max_batch,
        "clients": n_clients,
        "requests_per_round": n_requests,
        "rounds": rounds,
        "max_inflight": max_inflight,
        "run_max": run_max,
        "staging_slots": staging_slots,
        "fuse_ladder": list(fuse_ladder),
        "bitwise_ok": bitwise_ok,
        "single_dispatch_achieved_flops_per_s": round(single_dispatch_ref, 1),
        "ring_slots": ring_slots,
        "ring_min_fill": ring_min_fill,
        "ring_probe": ring_probe,
        "modes": modes,
        "overlapped_speedup_vs_sync": (
            round(modes["overlapped"]["qps"] / modes["sync"]["qps"], 4)
            if modes["sync"]["qps"] else None
        ),
        "ring_speedup_vs_sync": (
            round(modes["ring"]["qps"] / modes["sync"]["qps"], 4)
            if modes["sync"]["qps"] else None
        ),
        "cpu_rehearsal_note": _STRUCTURAL_CPU_CAVEAT,
    }


_QUANT_CPU_CAVEAT = (
    "cpu_rehearsal: QPS deltas between the wire modes are contention-noise on "
    "a 1-core box (the forward dominates; the transfer it shrinks is nearly "
    "free host-to-host). Unlike the overlap rounds, though, the HEADLINE "
    "claim here does not need an accelerator: per-request serve.h2d_bytes is "
    "registry math — the uint8 wire moves exactly 1/4 of the f32 wire's "
    "bytes on ANY host — and the parity verdicts (bitwise for the zero-mean "
    "denorm, measured max-abs delta under the configured atol otherwise, "
    "int8 top-1 agreement over the gate) are host-independent. The "
    "throughput win lands where H2D and HBM are real — the ROADMAP item 5 "
    "hardware rung. Note: random-init logits are a WORST CASE for top-1 "
    "agreement (near-ties everywhere, no trained margins), so the bench "
    "gate is configured below the production default."
)


def _quant_ab(net, folded, buckets, size, iters, rounds, rng, *,
              mean, std, top1_min):
    """The --quant measurement: ONE interleaved sweep over the three serving
    precisions — f32 (wire f32, weights f32), uint8-wire (wire u8, weights
    f32), and int8 (wire u8, weights int8) — at every bucket. Per mode:
    median-of-rounds QPS + p50/p99, per-request serve.h2d_bytes and
    serve.dispatched_bytes registry deltas (the transferred-byte and
    cost-byte instruments), and the parity verdicts: the zero-mean bitwise
    check, the mean/std wire delta vs the configured atol, and the int8
    export's gated top-1 agreement (serve/quant.py)."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.config import QuantConfig
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve import quant
    from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
    from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle

    wire_atol = QuantConfig().wire_atol  # the configured (production) gate
    reg = get_registry()
    bundle_f32 = InferenceBundle(net=net, params=folded, meta={})
    # the int8 export pass, gated exactly as cli/serve.py would run it:
    # seeded synthetic raw pixels normalized with the pipeline's mean/std
    calib_raw = rng.randint(0, 256, (32, size, size, 3)).astype(np.uint8)
    calib = quant.normalize_reference(calib_raw, mean, std)
    quantized, int8_report = quant.calibrate_and_quantize(
        net, folded, calib, top1_min=top1_min)
    bundle_int8 = InferenceBundle(net=net, params=quantized, meta={"quant": int8_report})

    common = dict(buckets=buckets, image_size=size, image_sizes=(size,), fuse_ladder=())
    engines = {
        "f32": InferenceEngine(bundle_f32, **common),
        "uint8_wire": InferenceEngine(bundle_f32, wire="uint8", wire_mean=mean,
                                      wire_std=std, **common),
        "int8": InferenceEngine(bundle_int8, wire="uint8", wire_mean=mean,
                                wire_std=std, **common),
    }
    for e in engines.values():
        e.warmup()

    # parity verdicts, all on one raw batch at the largest bucket
    cap = buckets[-1]
    raw = rng.randint(0, 256, (cap, size, size, 3)).astype(np.uint8)
    norm = quant.normalize_reference(raw, mean, std)
    ref = engines["f32"].predict(norm)
    got_u8 = engines["uint8_wire"].predict(raw)
    wire_delta = float(np.max(np.abs(got_u8 - ref)))
    # the bitwise regime: a zero-mean denorm is a single per-channel
    # multiply — pinned here with a dedicated identity-norm engine pair
    e_id_u8 = InferenceEngine(bundle_f32, wire="uint8", **common)
    id_bitwise = bool(np.array_equal(
        e_id_u8.predict(raw), engines["f32"].predict(quant.normalize_reference(raw))))
    got_int8 = engines["int8"].predict(raw)
    int8_top1 = float(np.mean(np.argmax(got_int8, -1) == np.argmax(ref, -1)))

    inputs = {
        "f32": {b: np.ascontiguousarray(norm[:b]) if b <= cap else None for b in buckets},
        "uint8_wire": {b: np.ascontiguousarray(raw[:b]) for b in buckets},
    }
    inputs["int8"] = inputs["uint8_wire"]
    per_bucket = []
    mode_tot = {m: {"h2d": 0.0, "cost": 0.0, "requests": 0} for m in engines}
    for b in buckets:
        row = {"batch": b}
        runs = {m: [] for m in engines}
        for e, x in ((engines[m], inputs[m][b]) for m in engines):
            e.predict(x)  # untimed page-in per mode
        for _ in range(rounds):
            for m, e in engines.items():  # interleaved: drift hits all alike
                x = inputs[m][b]
                s0 = reg.snapshot()
                lat = []
                for _ in range(iters):
                    t0 = time.perf_counter()
                    e.predict(x)
                    lat.append(time.perf_counter() - t0)
                s1 = reg.snapshot()
                lat.sort()
                runs[m].append((b / (sum(lat) / len(lat)), lat))
                mode_tot[m]["h2d"] += s1.get("serve.h2d_bytes", 0) - s0.get("serve.h2d_bytes", 0)
                mode_tot[m]["cost"] += (
                    s1.get("serve.dispatched_bytes", 0) - s0.get("serve.dispatched_bytes", 0))
                mode_tot[m]["requests"] += iters
        for m in engines:
            ordered = sorted(runs[m], key=lambda r: r[0])
            qps, lat = ordered[len(ordered) // 2]
            row[f"qps_{m}"] = round(qps, 2)
            row[f"p50_ms_{m}"] = round(_percentile(lat, 0.50) * 1e3, 3)
            row[f"p99_ms_{m}"] = round(_percentile(lat, 0.99) * 1e3, 3)
        per_bucket.append(row)

    modes = {}
    for m, e in engines.items():
        t = mode_tot[m]
        modes[m] = {
            "quant_mode": e.quant_mode,  # the build_info label this mode serves under
            "h2d_bytes_per_request": round(t["h2d"] / t["requests"], 1),
            "dispatched_bytes_per_request": round(t["cost"] / t["requests"], 1),
        }
    wire_ratio = (modes["f32"]["h2d_bytes_per_request"]
                  / modes["uint8_wire"]["h2d_bytes_per_request"])
    return {
        "image_size": size,
        "buckets": list(buckets),
        "rounds": rounds,
        "iters_per_round": iters,
        "mean": list(mean),
        "std": list(std),
        "per_bucket": per_bucket,
        "modes": modes,
        # the headline: transferred bytes per request, registry math. The
        # cost-analysis dispatched_bytes columns above are a COMPUTE-traffic
        # metric (they count the in-program dequant intermediates too), so
        # the residency win reads from int8_export.resident_shrink and the
        # transfer win from this ratio — docs/OBSERVABILITY.md.
        "wire_bytes_ratio": round(wire_ratio, 4),
        "parity": {
            "identity_norm_bitwise": id_bitwise,
            "wire_max_abs_logit_delta": round(wire_delta, 9),
            "wire_atol": wire_atol,
            "wire_parity_ok": wire_delta <= wire_atol,
            "int8_top1_agreement_calib": int8_report["top1_agreement"],
            "int8_top1_agreement_heldout": int8_top1,
            "int8_top1_min": top1_min,
        },
        "int8_export": {
            "quantized_tensors": int8_report["quantized_tensors"],
            "bytes_f32": int8_report["bytes_f32"],
            "bytes_int8": int8_report["bytes_int8"],
            "resident_shrink": round(
                int8_report["bytes_f32"] / int8_report["bytes_int8"], 4),
            "max_abs_logit_delta_calib": int8_report["max_abs_logit_delta"],
            "calib_images": int8_report["calib"]["images"],
        },
        "cpu_rehearsal_note": _QUANT_CPU_CAVEAT,
    }


_FLEET_CPU_CAVEAT = (
    "cpu_rehearsal: router, replicas, and load generator share this box's "
    "core(s), so absolute QPS and latency are contention-dominated. The "
    "pinned structural claims are the availability/accounting invariants "
    "(every submitted request resolves; a kill -9 costs retries+ejection, "
    "not client-visible failures), hedging firing at the measured-p-quantile "
    "timer with wins counted, and the autoscaler trace rising and falling "
    "with cooldown respected. Absolute fleet throughput is an accelerator "
    "measurement — same caveat discipline as r02/r04/r05."
)


def _fleet_round(router, image, *, n_requests, target_qps, seed,
                 mid_hook=None, mid_at=None, result_timeout_s=120.0):
    """One open-loop Poisson round through the fleet router. Arrivals fire
    on schedule regardless of completions; EVERY future is resolved at the
    end (a hang shows as ``unresolved`` > 0, never a stuck bench).
    ``mid_hook`` fires once before request index ``mid_at`` — the kill -9
    injection point."""
    from concurrent.futures import TimeoutError as FutTimeout

    import numpy as np

    from yet_another_mobilenet_series_tpu.serve.client import ClientHTTPError

    rs = np.random.RandomState(seed)
    gaps = rs.exponential(1.0 / target_qps, size=n_requests)
    pending = []
    lat = []
    lat_lock = threading.Lock()

    def _stamp(t0):
        # latency is stamped AT resolution (done callback), not when the
        # collector loop gets around to the future — otherwise every number
        # silently includes the remainder of the arrival schedule
        def cb(fut):
            if fut.exception() is None:
                with lat_lock:
                    lat.append(time.perf_counter() - t0)
        return cb

    t_start = time.perf_counter()
    t_next = t_start
    for i in range(n_requests):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if mid_hook is not None and i == mid_at:
            mid_hook()
            mid_hook = None
        t0 = time.perf_counter()
        fut = router.submit(image)
        fut.add_done_callback(_stamp(t0))
        pending.append(fut)
    out = {"submitted": n_requests, "completed": 0, "rejected": 0, "failed": 0,
           "unresolved": 0}
    for fut in pending:
        try:
            fut.result(timeout=result_timeout_s)
            out["completed"] += 1
        except FutTimeout:
            out["unresolved"] += 1  # a real hang: the router broke its contract
        except ClientHTTPError as e:
            out["rejected" if e.status < 500 else "failed"] += 1
        except Exception:  # noqa: BLE001 — typed route failure
            out["failed"] += 1
    wall = time.perf_counter() - t_start
    lat.sort()
    out.update({
        "wall_s": round(wall, 3),
        "qps": round(out["completed"] / wall, 2) if wall else 0.0,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
    })
    return out


def _fleet_registry_delta(reg, s0, keys):
    s1 = reg.snapshot()
    return {k.split(".", 1)[1]: int(s1.get(k, 0) - s0.get(k, 0)) for k in keys}


_FLEET_AB_KEYS = ("serve.hedges", "serve.hedge_wins", "serve.hedge_wasted",
                  "fleet.routed", "fleet.route_retries")
_FLEET_KILL_KEYS = ("fleet.route_retries", "fleet.ejections", "fleet.readmissions",
                    "fleet.restarts", "fleet.chaos_kills", "serve.hedges")


def measure_fleet(arch, image_size, buckets, *, replicas, requests, target_qps,
                  straggler_ms, seed, phase_s, log_root):
    """The ``--fleet`` measurement: a real fleet of cli/serve.py replica
    subprocesses behind the router tier (serve/router.py), exercised three
    ways on shared seeded schedules:

    1. **hedged vs unhedged A/B** — one straggler replica (highest slot)
       carries seeded injected completion latency (serve/faults.py), both
       rounds share one Poisson arrival schedule, and the hedged round arms
       the p-quantile timer (serve/hedge.py): ``serve.hedges`` fired,
       ``serve.hedge_wins`` first-answer wins, tail delta recorded.
    2. **kill -9 availability** — mid-round SIGKILL of a serving replica;
       the router's transport retry + ejection must account for EVERY
       submitted request as completed or typed-rejected (failed == 0, no
       client ever hangs), and the supervisor must restart the corpse.
    3. **autoscaler diurnal trace** — the fleet scales to 1, the straggler
       drains away, and a low/high/low open-loop schedule drives the
       Autoscaler (tail-latency + queue-depth signals, cooldown
       hysteresis): the N-over-time trace must rise under the peak and
       fall after it.
    """
    import jax
    import numpy as np

    from yet_another_mobilenet_series_tpu.cli.fleet import FleetSupervisor
    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.obs.fleet import FleetFederation, FlightRecorder
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry, quantiles_from_counts
    from yet_another_mobilenet_series_tpu.serve.autoscale import Autoscaler
    from yet_another_mobilenet_series_tpu.serve.export import export_bundle
    from yet_another_mobilenet_series_tpu.serve.hedge import Hedger
    from yet_another_mobilenet_series_tpu.serve.router import Router
    from yet_another_mobilenet_series_tpu.serve.signals import SLOTracker

    reg = get_registry()
    if arch == "tiny":  # same contract-test preset as measure()
        mc = ModelConfig(arch="mobilenet_v2", num_classes=16, dropout=0.0,
                         block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}, {"t": 2, "c": 16, "n": 1, "s": 2}])
    else:
        mc = ModelConfig(arch=arch)
    net = get_model(mc, image_size)
    params, state = net.init(jax.random.PRNGKey(0))
    bundle_dir = os.path.join(log_root, "bundle")
    export_bundle(net, params, state, bundle_dir)

    replica_argv = [
        f"serve.bundle={bundle_dir}",
        f"data.image_size={image_size}",
        f"serve.buckets=[{','.join(str(b) for b in buckets)}]",
        "serve.max_wait_ms=2.0",
        "serve.drain_timeout_s=10",
    ]
    straggler_slot = replicas - 1
    per_slot = {straggler_slot: [
        "serve.faults.enable=true",
        f"serve.faults.latency_ms={straggler_ms}",
        "serve.faults.latency_rate=0.3",
        "serve.faults.fail_at=result",
        f"serve.faults.seed={seed + 7}",
    ]}
    class _StderrLog:
        # the bench contract owns stdout (ONE JSON line); supervisor
        # progress goes to stderr like every other bench diagnostic
        def log(self, msg):
            print(msg, file=sys.stderr, flush=True)

    router = Router(poll_interval_s=0.25, eject_failures=2, route_attempts=3,
                    client_timeout_s=60.0, seed=seed).start()
    # fleet observability under measurement: the recorder hears every router
    # event from request #1 (the kill round's ejection is the incident
    # trigger), the federation scrapes on the bench's schedule (the bench IS
    # the single owner cli/fleet.py's main loop would otherwise be)
    recorder = FlightRecorder(log_root, min_interval_s=0.0)
    router.set_event_sink(recorder.record)
    federation = FleetFederation(router.backends, slo=SLOTracker(),
                                 recorder=recorder)
    fleet = FleetSupervisor(
        replica_argv=replica_argv, log_dir=log_root, replicas=replicas,
        per_slot_argv=per_slot, spawn_timeout_s=240.0, drain_timeout_s=30.0,
        on_change=router.set_backends, logger=_StderrLog(),
    )
    rng = np.random.RandomState(seed)
    image = rng.normal(0, 1, (image_size, image_size, 3)).astype("float32")
    out = {"replicas": replicas, "image_size": image_size, "seed": seed,
           "straggler": {"slot": straggler_slot, "latency_ms": straggler_ms,
                         "latency_rate": 0.3}}
    try:
        t0 = time.perf_counter()
        fleet.start()
        out["spawn_s"] = round(time.perf_counter() - t0, 2)

        # warm + calibrate: sequential closed-loop requests teach the router
        # latency histogram (the hedge timer's input) and give the pacing
        # p50. The timer quantile sits BELOW the straggler's hit rate
        # (~0.5 routing share x 0.3 injection) so the timer derives from the
        # fast cluster and fires well inside the injected stall.
        hedger = Hedger(quantile=0.8, min_samples=20, min_timer_ms=10.0)
        warm_lat = []
        for _ in range(40):
            t1 = time.perf_counter()
            router.submit(image).result(timeout=60)
            warm_lat.append(time.perf_counter() - t1)
        warm_lat.sort()
        p50_s = max(_percentile(warm_lat, 0.5), 1e-3)
        if target_qps <= 0:
            # well below the box's capacity: the A/B must measure the
            # straggler's tail, not open-loop queueing (which hedging
            # rightly cannot fix)
            target_qps = max(2.0, 0.35 / p50_s)
        out["warm_p50_ms"] = round(p50_s * 1e3, 3)
        out["target_qps"] = round(target_qps, 2)
        timer_s = hedger.timer_s("interactive")
        out["hedge_timer_ms"] = round(timer_s * 1e3, 3) if timer_s is not None else None

        # 1. hedged vs unhedged on one shared seeded schedule
        ab = {}
        for mode, h in (("unhedged", None), ("hedged", hedger)):
            router.set_hedger(h)
            s0 = reg.snapshot()
            rnd = _fleet_round(router, image, n_requests=requests,
                               target_qps=target_qps, seed=seed)
            # a hedge-won request's PRIMARY may still be inside the
            # straggler's stall: let the losers' late answers land (and be
            # counted dropped) before the delta is read
            time.sleep(2.5 * straggler_ms / 1e3)
            rnd.update(_fleet_registry_delta(reg, s0, _FLEET_AB_KEYS))
            ab[mode] = rnd
        router.set_hedger(None)
        ab["p99_ms_unhedged"] = ab["unhedged"]["p99_ms"]
        ab["p99_ms_hedged"] = ab["hedged"]["p99_ms"]
        ab["hedged_tail_speedup"] = (
            round(ab["unhedged"]["p99_ms"] / ab["hedged"]["p99_ms"], 4)
            if ab["hedged"]["p99_ms"] else None
        )
        out["hedge_ab"] = ab

        # 1b. federation correctness on live replicas: one scrape pins the
        # window baseline, a seeded round generates completions, and the
        # federated windowed p99 must EQUAL the pooled per-replica reference
        # recomputed here from THE SAME scraped documents — independent
        # delta/reset math, same quantiles_from_counts interpolation. Any
        # drift is a federation bug, not noise, so it raises.
        federation.scrape_once()
        docs0 = federation.last_varz()
        obs_rnd = _fleet_round(router, image, n_requests=max(30, requests // 2),
                               target_qps=target_qps, seed=seed + 11)
        federation.scrape_once()
        docs1 = federation.last_varz()
        fam = "serve.latency_seconds.interactive"
        pooled, bounds = None, None
        for key, doc in docs1.items():
            st = (doc.get("histograms") or {}).get(fam)
            if st is None:
                continue
            cur = [int(c) for c in st["counts"]]
            prev_st = ((docs0.get(key) or {}).get("histograms") or {}).get(fam)
            prev = [int(c) for c in prev_st["counts"]] if prev_st else None
            if prev is None or len(prev) != len(cur):
                delta = cur
            else:
                delta = [c - p for c, p in zip(cur, prev)]
                if any(d < 0 for d in delta):
                    delta = cur  # replica restarted: its whole history is the delta
            bounds = st["bounds"]
            pooled = delta if pooled is None else [a + d for a, d in zip(pooled, delta)]
        if pooled and sum(pooled):
            (pooled_p99_s,) = quantiles_from_counts(bounds, pooled, (0.99,))
        else:
            pooled_p99_s = 0.0
        fed_p99_s = reg.gauge("fleet.window_p99_seconds.interactive").value
        if abs(fed_p99_s - pooled_p99_s) > 1e-9:
            raise AssertionError(
                f"federated p99 {fed_p99_s} != pooled reference {pooled_p99_s}")
        obs = {
            "round": obs_rnd,
            "federated_p99_ms": round(fed_p99_s * 1e3, 3),
            "pooled_p99_ms": round(pooled_p99_s * 1e3, 3),
            "p99_match": True,
            "federated_replicas": len(docs1),
            "slo": federation.snapshot().get("slo"),
        }
        out["obs"] = obs

        # federation overhead on the submit path: the scrape loop hammers at
        # a cadence ~10x tighter than any real poll interval while
        # sequential submits measure p50. On this contention-dominated box
        # the delta is an upper bound (scraper and submitter share cores);
        # the structural claim is that the scrape never holds the router
        # lock, and the docs record the rehearsal number with that caveat.
        def _p50_submit(n=40):
            ts = []
            for _ in range(n):
                t1 = time.perf_counter()
                router.submit(image).result(timeout=60)
                ts.append(time.perf_counter() - t1)
            ts.sort()
            return max(_percentile(ts, 0.5), 1e-9)

        base_p50 = _p50_submit()
        stop_scrape = threading.Event()

        def _hammer():
            while not stop_scrape.is_set():
                federation.scrape_once()
                time.sleep(0.02)

        th = threading.Thread(target=_hammer, name="bench-scrape-hammer", daemon=True)
        th.start()
        try:
            scraped_p50 = _p50_submit()
        finally:
            stop_scrape.set()
            th.join(timeout=10)
        obs["submit_p50_ms"] = round(base_p50 * 1e3, 3)
        obs["submit_p50_ms_under_scrape"] = round(scraped_p50 * 1e3, 3)
        obs["federation_overhead_pct"] = round(
            (scraped_p50 - base_p50) / base_p50 * 100.0, 2)
        # the production-shaped number: mean scrape cost amortized over the
        # DEFAULT cadence (the router poll interval the supervisor rides,
        # config.py FleetObsConfig) — duty cycle, the fraction of wall time
        # federation occupies at all, an upper bound on submit inflation
        scrape_st = reg.histogram("fleet.scrape_seconds").state()
        scrape_mean_s = scrape_st["sum"] / max(scrape_st["count"], 1)
        cadence_s = 0.25  # serve.fleet.poll_interval_s default
        obs["scrape_mean_ms"] = round(scrape_mean_s * 1e3, 3)
        obs["amortized_overhead_pct"] = round(scrape_mean_s / cadence_s * 100.0, 3)

        # 2. kill -9 a serving (non-straggler) replica mid-round: the books
        # must balance with zero client-visible failures, and the
        # supervisor must restart the corpse
        s0 = reg.snapshot()

        def _chaos_kill():
            # the injector announces its own fault to the flight recorder:
            # arming here is deterministic, where the router-side ejection
            # trigger races the supervisor's set_backends (which usually
            # removes the corpse before enough failures accrue to eject)
            recorder.trigger("chaos_kill")
            fleet.kill_replica(slot=0, sig=signal.SIGKILL)

        kill = _fleet_round(
            router, image, n_requests=requests, target_qps=target_qps, seed=seed + 1,
            mid_at=requests // 3,
            mid_hook=_chaos_kill,
        )
        # bounded wait for the restart to land (counts fleet.restarts)
        deadline = time.monotonic() + 120
        while len(fleet.addresses()) < replicas and time.monotonic() < deadline:
            time.sleep(0.25)
        kill.update(_fleet_registry_delta(reg, s0, _FLEET_KILL_KEYS))
        kill["replicas_after_restart"] = len(fleet.addresses())
        out["kill"] = kill

        # the chaos trigger armed the flight recorder (plus any natural
        # ejection event in the ring): one more scrape for a fresh federated
        # snapshot, then the dump — the incident artifact (event ring +
        # fleet snapshot + per-replica /varz) the round pins
        federation.scrape_once()
        incident = recorder.maybe_dump(federation)
        obs["incident"] = os.path.basename(incident) if incident else None
        if incident:
            with open(incident) as f:
                idoc = json.load(f)
            obs["incident_reason"] = idoc["reason"]
            obs["incident_events"] = len(idoc["events"])
            obs["incident_has_fleet_snapshot"] = "fleet" in idoc and "replica_varz" in idoc

        # 3. autoscaler over a diurnal low/high/low open-loop schedule,
        # starting from one clean replica (the straggler drains first).
        # Thresholds calibrate off the A/B round's OPEN-LOOP p50 — the
        # sequential warm p50 is dominated by per-request HTTP overhead the
        # concurrent path pipelines away, so it would set the bar far above
        # anything the peak can reach.
        fleet.scale_to(1)
        router.poll_once()
        ab_p50_ms = max(ab["unhedged"]["p50_ms"], 1.0)
        low_s, high_s, trough_s = phase_s
        autoscaler = Autoscaler(
            fleet, router,
            min_replicas=1, max_replicas=min(replicas + 1, 3),
            interval_s=0.4, cooldown_s=1.5,
            # the dead band separates this box's measured light-traffic
            # windows (~5-10ms p99) from its saturated ones (>= ~50ms,
            # often seconds): up above the idle ceiling, down below it
            up_p99_ms=max(6.0 * ab_p50_ms, 30.0),
            down_p99_ms=max(2.5 * ab_p50_ms, 12.0),
            up_queue_depth=2.0, down_queue_depth=1.0,
        ).start()
        # the peak must EXCEED what the box can serve (router + replicas +
        # load gen share its cores), so the latency windows really rise
        phases = [(0.4 * target_qps, low_s),
                  (12.0 * target_qps, high_s),
                  (0.4 * target_qps, trough_s)]
        diurnal = []
        for i, (qps, dur) in enumerate(phases):
            n = max(4, int(qps * dur))
            rnd = _fleet_round(router, image, n_requests=n, target_qps=qps,
                               seed=seed + 2 + i)
            diurnal.append({"phase": ("low", "high", "trough")[i],
                            "target_qps": round(qps, 2), **rnd})
        # let the trough's relaxed signals finish the scale-down
        settle_until = time.monotonic() + 3 * autoscaler._cooldown_s
        while time.monotonic() < settle_until:
            time.sleep(0.3)
        autoscaler.stop()
        trace = autoscaler.trace
        ns = [r["n"] for r in trace]
        action_ts = [r["t"] for r in trace if r["action"] != "hold"]
        out["autoscale"] = {
            "min_replicas": autoscaler.min_replicas,
            "max_replicas": autoscaler.max_replicas,
            "cooldown_s": autoscaler._cooldown_s,
            "phases": diurnal,
            "trace": trace,
            "n_start": ns[0] if ns else None,
            "n_peak": max(ns) if ns else None,
            "n_end": ns[-1] if ns else None,
            "actions": [r for r in trace if r["action"] != "hold"],
            "cooldown_respected": all(
                b - a >= 0.9 * autoscaler._cooldown_s
                for a, b in zip(action_ts, action_ts[1:])
            ),
        }
        out["cpu_rehearsal_note"] = _FLEET_CPU_CAVEAT
        return out
    finally:
        router.stop()
        fleet.stop()


_ZOO_CPU_CAVEAT = (
    "cpu_rehearsal: both replicas, the router, the cascade policy, and the "
    "load generator share this box's core(s), so absolute latency/QPS are "
    "contention-dominated. The pinned structural claims are "
    "host-independent: the model-sharded arm shows ZERO misroutes (per-"
    "replica serve.model_requests deltas) and zero 5xx on the same seeded "
    "trace; the cascade arm escalates > 0 requests, every answer is "
    "bitwise one of the two per-image references (escalated answers EQUAL "
    "the big-only arm's), and its fleet-wide dispatched-FLOPs/request mean "
    "sits strictly below the big-only arm's. Wall-clock speedups are an "
    "accelerator measurement — same caveat discipline as r06/r07."
)


def _zoo_scrape_flops(router):
    """Sum ``serve.dispatched_flops`` across every replica's /varz registry
    snapshot. Dispatch cost is engine-side (per replica process), so per-arm
    deltas of this sum are the fleet-wide dispatched cost — the cascade's
    cost-proxy instrument."""
    total, per = 0.0, {}
    for key, client in router.backends():
        _status, doc = client.varz(timeout_s=10.0)
        v = float(((doc or {}).get("metrics") or {}).get("serve.dispatched_flops", 0))
        per[key] = v
        total += v
    return total, per


def _zoo_scrape_model_requests(router, models):
    """Per-replica ``serve.model_requests.{model}`` counters — the misroute
    instrument: on a model-sharded fleet a replica must never count a
    request for a model it does not serve."""
    per = {}
    for key, client in router.backends():
        _status, doc = client.varz(timeout_s=10.0)
        met = (doc or {}).get("metrics") or {}
        per[key] = {m: int(met.get(f"serve.model_requests.{m}", 0)) for m in models}
    return per


def _zoo_round(submit, images, models, *, target_qps, seed, result_timeout_s=120.0):
    """One open-loop Poisson round over a FIXED per-index plan: request i
    submits ``images[i]`` pinned to ``models[i]`` (None = unqualified — the
    cascade decides the tier). Latency stamps at resolution like
    ``_fleet_round``; answers come back INDEXED so the caller can check
    every one bitwise against its per-image reference."""
    from concurrent.futures import TimeoutError as FutTimeout

    import numpy as np

    from yet_another_mobilenet_series_tpu.serve.client import ClientHTTPError

    rs = np.random.RandomState(seed)
    n = len(images)
    gaps = rs.exponential(1.0 / target_qps, size=n)
    pending = []
    lat = {}
    lat_lock = threading.Lock()

    def _stamp(i, t0):
        def cb(fut):
            if fut.exception() is None:
                with lat_lock:
                    lat[i] = time.perf_counter() - t0
        return cb

    t_start = time.perf_counter()
    t_next = t_start
    for i in range(n):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t0 = time.perf_counter()
        fut = submit(images[i], models[i])
        fut.add_done_callback(_stamp(i, t0))
        pending.append(fut)
    out = {"submitted": n, "completed": 0, "rejected": 0, "failed": 0,
           "unresolved": 0}
    answers = [None] * n
    for i, fut in enumerate(pending):
        try:
            answers[i] = np.asarray(fut.result(timeout=result_timeout_s))
            out["completed"] += 1
        except FutTimeout:
            out["unresolved"] += 1  # a real hang: the tier broke its contract
        except ClientHTTPError as e:
            out["rejected" if e.status < 500 else "failed"] += 1
        except Exception:  # noqa: BLE001 — typed route failure
            out["failed"] += 1
    wall = time.perf_counter() - t_start
    per_model = {}
    for i, m in enumerate(models):
        if i in lat:
            per_model.setdefault(m or "cascade", []).append(lat[i])
    all_lat = sorted(lat.values())
    out.update({
        "wall_s": round(wall, 3),
        "qps": round(out["completed"] / wall, 2) if wall else 0.0,
        "p50_ms": round(_percentile(all_lat, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(all_lat, 0.99) * 1e3, 3),
        "per_model": {
            m: {"n": len(v),
                "p50_ms": round(_percentile(sorted(v), 0.50) * 1e3, 3),
                "p99_ms": round(_percentile(sorted(v), 0.99) * 1e3, 3)}
            for m, v in sorted(per_model.items())
        },
    })
    return out, answers


def measure_zoo(arch, image_size, *, requests, target_qps, seed, threshold,
                int8_top1_min, log_root):
    """The ``--zoo`` measurement: ONE 2-replica model-sharded fleet — slot 0
    serves the int8 'small' tier, slot 1 the f32 'big' tier, via per-slot
    ``serve.zoo.models`` assignments with the placement advertised to the
    router — A/B'd three ways over ONE seeded trace of images:

    1. **big_only** — every request pinned ``X-Model: big``: the
       one-model-per-fleet cost/latency baseline.
    2. **sharded** — a seeded 50/50 model-pin mix through the model-aware
       pick; per-replica ``serve.model_requests.{model}`` deltas must show
       ZERO misroutes and the books must show zero 5xx.
    3. **cascade** — unqualified submits through serve/cascade.py: the
       small tier answers confident requests, low-margin ones re-submit to
       the big tier. Escalations must be > 0, every answer must be bitwise
       one of the two per-image references (escalated answers EQUAL the
       big-only arm's), and the fleet-wide dispatched-FLOPs/request mean
       must sit STRICTLY below the big_only arm's.

    The threshold defaults to the MEDIAN reference margin, so both cascade
    outcomes (answered-small and escalated) are populated by construction.
    Buckets are pinned to [1] so every arm's answers are bitwise-comparable
    against the explicit-pin reference pass by construction (no padding
    variation between arms)."""
    import jax
    import numpy as np

    from yet_another_mobilenet_series_tpu.cli.fleet import FleetSupervisor
    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve.cascade import CascadeTier, softmax_margin
    from yet_another_mobilenet_series_tpu.serve.export import export_bundle
    from yet_another_mobilenet_series_tpu.serve.router import Router

    reg = get_registry()
    rng = np.random.RandomState(seed)
    # two genuinely different cost tiers: the small tier is the contract-test
    # tiny preset (int8 weights), the big tier is deeper/wider so the
    # cascade's FLOPs win is structural, not noise
    small_mc = ModelConfig(arch="mobilenet_v2", num_classes=16, dropout=0.0,
                           block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2},
                                        {"t": 2, "c": 16, "n": 1, "s": 2}])
    if arch == "tiny":
        big_mc = ModelConfig(arch="mobilenet_v2", num_classes=16, dropout=0.0,
                             block_specs=[{"t": 4, "c": 24, "n": 2, "s": 2},
                                          {"t": 4, "c": 48, "n": 2, "s": 2},
                                          {"t": 4, "c": 96, "n": 1, "s": 1}])
    else:
        big_mc = ModelConfig(arch=arch)
    small_net = get_model(small_mc, image_size)
    sp, ss = small_net.init(jax.random.PRNGKey(seed))
    calib = rng.normal(0, 1, (8, image_size, image_size, 3)).astype("float32")
    small_dir = os.path.join(log_root, "small")
    export_bundle(small_net, sp, ss, small_dir, model_name="small",
                  quant_weights="int8", calib_images=calib,
                  int8_top1_min=int8_top1_min)
    big_net = get_model(big_mc, image_size)
    bp, bs = big_net.init(jax.random.PRNGKey(seed + 1))
    big_dir = os.path.join(log_root, "big")
    export_bundle(big_net, bp, bs, big_dir, model_name="big")

    def _meta(d):
        with open(os.path.join(d, "meta.json")) as f:
            return json.load(f)

    small_meta, big_meta = _meta(small_dir), _meta(big_dir)

    base_argv = [
        f"data.image_size={image_size}",
        "serve.buckets=[1]",  # bucket-1 everywhere: bitwise identity by construction
        "serve.max_wait_ms=1.0",
        "serve.drain_timeout_s=10",
    ]
    # model-sharded placement: each slot serves exactly one tenant — the
    # per-slot argv is the same shape cli/fleet.py slot_overrides() emits
    per_slot = {
        0: [f"serve.zoo.models=small={small_dir}", "serve.zoo.default=small"],
        1: [f"serve.zoo.models=big={big_dir}", "serve.zoo.default=big"],
    }
    slot_adverts = {0: {"small": small_meta.get("digest", "")},
                    1: {"big": big_meta.get("digest", "")}}

    class _StderrLog:
        # the bench contract owns stdout (ONE JSON line)
        def log(self, msg):
            print(msg, file=sys.stderr, flush=True)

    router = Router(poll_interval_s=0.25, eject_failures=2, route_attempts=3,
                    client_timeout_s=60.0, seed=seed).start()
    fleet_ref = {}

    def _on_change(addrs):
        # membership AND placement ride every supervisor notification: the
        # router learns which tenant each address serves (digest-stamped),
        # exactly what cli/fleet.py's placement wiring does
        router.set_backends(addrs)
        fleet = fleet_ref.get("fleet")
        if fleet is None:
            return
        assignments = {}
        for r in fleet.replicas():
            addr = r["addr"]
            if addr is not None:
                key = f"{addr['host']}:{addr['port']}"
                assignments[key] = slot_adverts[r["slot"] % 2]
        router.set_backend_models(assignments)

    fleet = FleetSupervisor(
        replica_argv=base_argv, log_dir=log_root, replicas=2,
        per_slot_argv=per_slot, spawn_timeout_s=240.0, drain_timeout_s=30.0,
        on_change=_on_change, logger=_StderrLog(),
    )
    fleet_ref["fleet"] = fleet
    out = {"replicas": 2, "image_size": image_size, "seed": seed,
           "requests": requests,
           "models": {
               "small": {"weights": "int8",
                         "digest": small_meta.get("digest", "")[:12],
                         "int8_top1": (small_meta.get("quant") or {}).get("top1_agreement")},
               "big": {"weights": "float32",
                       "digest": big_meta.get("digest", "")[:12]},
           }}
    try:
        t0 = time.perf_counter()
        fleet.start()
        out["spawn_s"] = round(time.perf_counter() - t0, 2)
        slot_addr = {r["slot"]: r["addr"] for r in fleet.replicas()
                     if r["addr"] is not None}
        small_key = f"{slot_addr[0]['host']}:{slot_addr[0]['port']}"
        big_key = f"{slot_addr[1]['host']}:{slot_addr[1]['port']}"
        out["placement"] = {small_key: ["small"], big_key: ["big"]}

        images = [rng.normal(0, 1, (image_size, image_size, 3)).astype("float32")
                  for _ in range(requests)]

        # reference pass: every trace image answered by BOTH tiers via
        # explicit pins — the per-image bitwise references for all three
        # arms, and the margins that calibrate the cascade threshold
        refs_small, refs_big, margins, warm_lat = [], [], [], []
        for img in images:
            t1 = time.perf_counter()
            r = router.submit(img, model="small").result(timeout=120)
            warm_lat.append(time.perf_counter() - t1)
            refs_small.append(np.asarray(r))
            margins.append(softmax_margin(r))
        for img in images:
            refs_big.append(np.asarray(
                router.submit(img, model="big").result(timeout=120)))
        if threshold is None or threshold < 0:
            # the median margin splits the trace: ~half answer small, ~half
            # escalate — both cascade outcomes populated by construction
            threshold = float(np.median(margins))
        out["threshold"] = round(threshold, 6)
        out["margins"] = {"min": round(float(np.min(margins)), 6),
                          "median": round(float(np.median(margins)), 6),
                          "max": round(float(np.max(margins)), 6)}
        warm_lat.sort()
        p50_s = max(_percentile(warm_lat, 0.5), 1e-3)
        if target_qps <= 0:
            target_qps = max(2.0, 0.35 / p50_s)
        out["target_qps"] = round(target_qps, 2)

        arms = {}
        # arm 1: one-model-per-fleet baseline — everything pinned big
        f0, _ = _zoo_scrape_flops(router)
        rnd, ans = _zoo_round(lambda img, m: router.submit(img, model=m),
                              images, ["big"] * requests,
                              target_qps=target_qps, seed=seed + 2)
        f1, _ = _zoo_scrape_flops(router)
        rnd["flops_per_request"] = (f1 - f0) / max(rnd["completed"], 1)
        rnd["bitwise_match_big"] = all(
            a is not None and np.array_equal(a, refs_big[i])
            for i, a in enumerate(ans))
        arms["big_only"] = rnd

        # arm 2: model-sharded 50/50 pins — the zero-misroute/zero-5xx claim
        mix_rs = np.random.RandomState(seed + 3)
        mix = ["small" if mix_rs.rand() < 0.5 else "big" for _ in range(requests)]
        mix[0], mix[1] = "small", "big"  # both tenants always present
        mr0 = _zoo_scrape_model_requests(router, ("small", "big"))
        f0, _ = _zoo_scrape_flops(router)
        rnd, ans = _zoo_round(lambda img, m: router.submit(img, model=m),
                              images, mix, target_qps=target_qps, seed=seed + 4)
        f1, _ = _zoo_scrape_flops(router)
        mr1 = _zoo_scrape_model_requests(router, ("small", "big"))
        rnd["flops_per_request"] = (f1 - f0) / max(rnd["completed"], 1)
        rnd["mix"] = {"small": mix.count("small"), "big": mix.count("big")}
        # a misroute is a request METERED on the replica that does not
        # serve its model — admission counts serve.model_requests.{m} at
        # the replica door, so the cross deltas must both be zero
        rnd["misroutes"] = (
            (mr1[small_key]["big"] - mr0[small_key]["big"])
            + (mr1[big_key]["small"] - mr0[big_key]["small"]))
        rnd["bitwise_match"] = all(
            a is not None and np.array_equal(
                a, (refs_small if mix[i] == "small" else refs_big)[i])
            for i, a in enumerate(ans))
        arms["sharded"] = rnd
        if rnd["misroutes"] != 0 or rnd["failed"] != 0 or rnd["unresolved"] != 0:
            raise AssertionError(
                f"sharded arm broke placement: misroutes={rnd['misroutes']} "
                f"failed={rnd['failed']} unresolved={rnd['unresolved']}")

        # arm 3: the confidence cascade over the SAME sharded fleet
        tier = CascadeTier(router, small="small", big="big", threshold=threshold)
        s0 = reg.snapshot()
        f0, _ = _zoo_scrape_flops(router)
        rnd, ans = _zoo_round(lambda img, _m: tier.submit(img), images,
                              [None] * requests, target_qps=target_qps,
                              seed=seed + 5)
        f1, _ = _zoo_scrape_flops(router)
        s1 = reg.snapshot()

        def _d(key):
            return int(s1.get(key, 0) - s0.get(key, 0))

        esc = _d("serve.cascade.escalations")
        rnd["escalations"] = esc
        rnd["answered_small"] = _d("serve.cascade.answered_small")
        rnd["deadline_skips"] = _d("serve.cascade.deadline_skips")
        rnd["escalation_failures"] = _d("serve.cascade.escalation_failures")
        decided = esc + rnd["answered_small"]
        rnd["escalation_rate"] = round(esc / decided, 4) if decided else 0.0
        rnd["flops_per_request"] = (f1 - f0) / max(rnd["completed"], 1)
        # bitwise discipline: every answer must equal EXACTLY one of the two
        # per-image references, and the big-matches must equal the counted
        # escalations (minus any failures, which must be zero anyway)
        esc_matches = small_matches = mismatches = 0
        for i, a in enumerate(ans):
            if a is None:
                continue
            if np.array_equal(a, refs_small[i]):
                small_matches += 1
            elif np.array_equal(a, refs_big[i]):
                esc_matches += 1
            else:
                mismatches += 1
        rnd["answers_big_bitwise"] = esc_matches
        rnd["answers_small_bitwise"] = small_matches
        rnd["answer_mismatches"] = mismatches
        rnd["escalated_bitwise_match_big_only"] = (
            mismatches == 0 and esc_matches == esc - rnd["escalation_failures"])
        arms["cascade"] = rnd
        if esc <= 0 or rnd["answered_small"] <= 0:
            raise AssertionError(
                f"cascade did not split the trace: escalations={esc} "
                f"answered_small={rnd['answered_small']}")
        if mismatches:
            raise AssertionError(
                f"{mismatches} cascade answers matched NEITHER reference")

        out["arms"] = arms
        big_fpr = arms["big_only"]["flops_per_request"]
        ratio = (arms["cascade"]["flops_per_request"] / big_fpr) if big_fpr else None
        out["cost"] = {
            "big_only_flops_per_request": round(big_fpr, 1),
            "sharded_flops_per_request": round(arms["sharded"]["flops_per_request"], 1),
            "cascade_flops_per_request": round(arms["cascade"]["flops_per_request"], 1),
            "cascade_vs_big_only": round(ratio, 4) if ratio is not None else None,
        }
        # the acceptance criterion the whole subsystem exists for: at ~half
        # escalation rate the blended cost must beat all-big STRICTLY
        if ratio is None or ratio >= 1.0:
            raise AssertionError(
                f"cascade flops/request did not beat big-only: ratio={ratio}")
        for arm in arms.values():
            if arm["unresolved"]:
                raise AssertionError("a zoo arm left futures unresolved")
        out["cpu_rehearsal_note"] = _ZOO_CPU_CAVEAT
        return out
    finally:
        router.stop()
        fleet.stop()


_OVERLOAD_CPU_CAVEAT = (
    "cpu_rehearsal: engine, batcher, controller, and load generator share "
    "this box's core(s), so absolute QPS/latency are contention-dominated. "
    "The pinned structural claims are host-independent: interactive-class "
    "availability under the SAME seeded 3x-capacity storm is higher with "
    "the brownout ladder on than off, the ladder steps up during the storm "
    "and fully recovers to L0 after it, every submitted future resolves "
    "(zero unresolved), and the gray-failure round shows the latency-based "
    "soft ejection firing within the configured window followed by tail "
    "recovery. Absolute capacity is an accelerator measurement — the same "
    "caveat discipline as r02/r04/r05/r06."
)

_OVERLOAD_CLASS_MIX = {"interactive": 0.4, "batch": 0.2, "best_effort": 0.4}


def _overload_round(admission, images, *, seed, n_requests, target_qps,
                    deadline_ms_by_class):
    """One open-loop Poisson storm through an admission controller. Same
    discipline as ``_chaos_round``: pre-drawn arrivals fire on schedule,
    EVERY future resolves (a hang is ``unresolved`` > 0), per-class books
    balance. Latencies are stamped at resolution via callbacks so the p99
    does not silently include the tail of the arrival schedule."""
    from concurrent.futures import TimeoutError as FutTimeout

    import numpy as np

    from yet_another_mobilenet_series_tpu.serve.batcher import DeadlineExceeded, DrainTimeout

    rs = np.random.RandomState(seed)
    classes, probs = zip(*sorted(_OVERLOAD_CLASS_MIX.items()))
    draws_cls = [classes[i] for i in rs.choice(len(classes), size=n_requests, p=probs)]
    gaps = rs.exponential(1.0 / target_qps, size=n_requests)
    stats = {c: {"submitted": 0, "completed": 0, "rejected": 0, "shed": 0, "failed": 0}
             for c in classes}
    lat = {c: [] for c in classes}
    lat_lock = threading.Lock()
    pending = []
    t_start = time.perf_counter()
    t_next = t_start
    for i in range(n_requests):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule paces us, not completions
        cls = draws_cls[i]
        stats[cls]["submitted"] += 1
        t0 = time.perf_counter()
        try:
            fut = admission.submit(images[cls], priority=cls,
                                   deadline_ms=deadline_ms_by_class.get(cls))
        except Exception:  # noqa: BLE001 — typed arrival rejection (quota/brownout/deadline)
            stats[cls]["rejected"] += 1
            continue

        def _stamp(fut, cls=cls, t0=t0):
            if fut.exception() is None:
                with lat_lock:
                    lat[cls].append(time.perf_counter() - t0)

        fut.add_done_callback(_stamp)
        pending.append((cls, fut))
    unresolved = 0
    for cls, fut in pending:
        try:
            fut.result(timeout=300)
            stats[cls]["completed"] += 1
        except (DeadlineExceeded, DrainTimeout):
            stats[cls]["shed"] += 1
        except FutTimeout:
            unresolved += 1  # a real hang: the no-client-ever-hangs invariant broke
        except Exception:  # noqa: BLE001 — typed rejection or engine failure
            stats[cls]["failed"] += 1
    wall = time.perf_counter() - t_start
    out = {"wall_s": round(wall, 3), "unresolved": unresolved, "classes": {}}
    for cls in classes:
        s = stats[cls]
        ls = sorted(lat[cls])
        avail = s["completed"] / s["submitted"] if s["submitted"] else None
        out["classes"][cls] = {
            **s,
            "availability": round(avail, 4) if avail is not None else None,
            "p50_ms": round(_percentile(ls, 0.50) * 1e3, 3),
            "p99_ms": round(_percentile(ls, 0.99) * 1e3, 3),
        }
    return out


def measure_overload(arch, image_size, buckets, *, storm_s, multiple, seed,
                     pace_ms, replicas, gray_requests, straggler_ms, log_root):
    """The ``--overload`` measurement, two halves:

    1. **brownout A/B** (in-process): ONE seeded open-loop Poisson storm at
       ``multiple`` x the measured closed-loop capacity, run twice through
       fresh batcher+admission stacks — brownout OFF vs ON — with
       interactive deadlines derived from the warm p50. The engine is
       PACED (seeded FaultyEngine latency floor of ``pace_ms`` per
       dispatch) so capacity is deterministic on any box — a tiny model on
       a fast host would otherwise absorb any finite storm before the
       ladder could tick. The pinned claim: interactive availability
       (completed/submitted) is higher with the ladder on, the ladder
       steps up under the storm and fully recovers to L0 after it, and
       nothing hangs in either arm.
    2. **gray-failure round** (real fleet): replica subprocesses behind the
       router, the highest slot latency-injected (slow-but-alive, never
       crashing). Soft ejection is armed at the round start (a known t0),
       so time-to-eject is measured, and completion-stamped latencies
       split at the ejection instant pin the tail recovering after it.
    """
    import jax
    import numpy as np

    from yet_another_mobilenet_series_tpu.cli.fleet import FleetSupervisor
    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.brownout import BrownoutController
    from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
    from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle, export_bundle, fold_network
    from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher
    from yet_another_mobilenet_series_tpu.serve.router import Router
    from yet_another_mobilenet_series_tpu.serve.signals import SignalReader

    reg = get_registry()
    if arch == "tiny":  # same contract-test preset as measure()
        mc = ModelConfig(arch="mobilenet_v2", num_classes=16, dropout=0.0,
                         block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}, {"t": 2, "c": 16, "n": 1, "s": 2}])
    else:
        mc = ModelConfig(arch=arch)
    net = get_model(mc, image_size)
    params, state = net.init(jax.random.PRNGKey(0))
    bundle = InferenceBundle(net=net, params=fold_network(net, params, state), meta={})
    engine = InferenceEngine(bundle, buckets=buckets, image_size=image_size)
    engine.warmup()
    # deterministic capacity ceiling: every dispatch pays pace_ms at sync,
    # so "3x capacity" means the same storm on a laptop and a server
    paced = FaultyEngine(engine, seed=seed, latency_s=pace_ms / 1e3, latency_rate=1.0)
    rng = np.random.RandomState(seed)
    images = {c: rng.normal(0, 1, (image_size, image_size, 3)).astype("float32")
              for c in _OVERLOAD_CLASS_MIX}
    max_batch = max(buckets)
    out = {"image_size": image_size, "seed": seed, "storm_s": storm_s,
           "pace_ms": pace_ms, "class_mix": dict(_OVERLOAD_CLASS_MIX)}

    def _stack():
        b = PipelinedBatcher(paced, max_batch=max_batch, max_wait_ms=5.0,
                             queue_depth=128, drain_timeout_s=60.0).start()
        a = AdmissionController(b, max_retries=1, retry_backoff_ms=5.0,
                                breaker_threshold=50, breaker_cooldown_s=0.5, seed=seed)
        return b, a

    # -- capacity calibration (closed loop, brownout off) --------------------
    b, a = _stack()
    warm_lat = []
    n_warm, n_clients = 48, max_batch

    def _warm_client(n):
        img = images["interactive"]
        for _ in range(n):
            t0 = time.perf_counter()
            a.submit(img, priority="interactive").result(timeout=60)
            warm_lat.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=_warm_client, args=(n_warm // n_clients,), daemon=True)
               for _ in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    warm_wall = time.perf_counter() - t0
    b.stop()
    warm_lat.sort()
    capacity_qps = len(warm_lat) / warm_wall if warm_wall > 0 else 1.0
    p50_ms = max(_percentile(warm_lat, 0.5) * 1e3, 0.5)
    storm_qps = multiple * capacity_qps
    # duration-driven storm: the ladder needs seconds of sustained overload
    # to climb, so the request count follows the rate, not vice versa
    requests = max(40, int(storm_qps * storm_s))
    out["requests"] = requests
    # interactive deadline: far above the healthy latency, far below what a
    # sustained 3x backlog produces — the availability instrument
    interactive_deadline_ms = max(8.0 * p50_ms, 100.0)
    deadlines = {"interactive": interactive_deadline_ms}
    out["capacity"] = {
        "closed_loop_qps": round(capacity_qps, 2), "clients": n_clients,
        "warm_p50_ms": round(p50_ms, 3), "storm_qps": round(storm_qps, 2),
        "multiple": multiple,
        "interactive_deadline_ms": round(interactive_deadline_ms, 1),
    }

    # -- the A/B: one seeded storm, brownout off vs on -----------------------
    arms = {}
    for mode in ("off", "on"):
        b, a = _stack()
        controller = None
        if mode == "on":
            controller = BrownoutController(
                SignalReader(latency_family="serve.latency_seconds",
                             signal_class="interactive",
                             queue_depth_fn=a.queued_total),
                (b, a),
                interval_s=0.1,
                up_p99_ms=max(4.0 * p50_ms, 40.0),
                down_p99_ms=max(1.5 * p50_ms, 10.0),
                up_queue_depth=1.5 * max_batch,
                down_queue_depth=0.5 * max_batch,
                hold_up_s=0.3, cooldown_s=0.5,
                retry_after_s=1.0,
                # stdout is the ONE-JSON-line artifact: transitions -> stderr
                log_fn=lambda m: print(m, file=sys.stderr, flush=True),
            ).start()
        s0 = reg.snapshot()
        rnd = _overload_round(a, images, seed=seed + 1, n_requests=requests,
                              target_qps=storm_qps, deadline_ms_by_class=deadlines)
        s1 = reg.snapshot()
        rnd["shed_at_door_brownout"] = int(s1.get("serve.rejected_brownout", 0)
                                           - s0.get("serve.rejected_brownout", 0))
        if controller is not None:
            # recovery: idle windows are relaxed; one level per cooldown
            settle_until = time.monotonic() + 6 * controller._cooldown_s + 2.0
            while controller.level > 0 and time.monotonic() < settle_until:
                time.sleep(0.1)
            trace = controller.trace
            controller.stop()
            rnd["brownout"] = {
                "peak_level": max((r["level"] for r in trace), default=0),
                "final_level": trace[-1]["level"] if trace else None,
                "recovered_to_l0": bool(trace and trace[-1]["level"] == 0),
                "transitions_up": sum(1 for r in trace if r["action"] == "up"),
                "transitions_down": sum(1 for r in trace if r["action"] == "down"),
                "trace": trace,
            }
        b.stop()
        arms[mode] = rnd
    out["storm"] = {
        "off": arms["off"], "on": arms["on"],
        "interactive_availability_off": arms["off"]["classes"]["interactive"]["availability"],
        "interactive_availability_on": arms["on"]["classes"]["interactive"]["availability"],
    }

    # -- gray failure: slow-but-alive replica, soft ejection + recovery ------
    bundle_dir = os.path.join(log_root, "bundle")
    export_bundle(net, params, state, bundle_dir)
    replica_argv = [
        f"serve.bundle={bundle_dir}",
        f"data.image_size={image_size}",
        f"serve.buckets=[{','.join(str(x) for x in buckets)}]",
        "serve.max_wait_ms=2.0",
        "serve.drain_timeout_s=10",
    ]
    straggler_slot = replicas - 1
    per_slot = {straggler_slot: [
        "serve.faults.enable=true",
        f"serve.faults.latency_ms={straggler_ms}",
        "serve.faults.latency_rate=1.0",  # EVERY dispatch is slow: gray, not flaky
        "serve.faults.fail_at=result",
        f"serve.faults.seed={seed + 7}",
    ]}

    class _StderrLog:
        def log(self, msg):
            print(msg, file=sys.stderr, flush=True)

    # soft ejection configured but DISARMED for the warm phase: arming it at
    # the round start gives time-to-eject a known zero point
    router = Router(poll_interval_s=0.25, eject_failures=2, route_attempts=3,
                    client_timeout_s=60.0, seed=seed,
                    slow_eject=False, slow_factor=3.0, slow_eject_after=3,
                    slow_cooldown_s=60.0, slow_min_ms=1.0)
    fleet = FleetSupervisor(
        replica_argv=replica_argv, log_dir=log_root, replicas=replicas,
        per_slot_argv=per_slot, spawn_timeout_s=240.0, drain_timeout_s=30.0,
        on_change=router.set_backends, logger=_StderrLog(),
    )
    gray = {"replicas": replicas, "straggler": {"slot": straggler_slot,
                                                "latency_ms": straggler_ms,
                                                "latency_rate": 1.0}}
    try:
        t0 = time.perf_counter()
        fleet.start()
        router.start()
        gray["spawn_s"] = round(time.perf_counter() - t0, 2)
        img = images["interactive"]
        warm = []
        for _ in range(24):  # teaches every replica's per-leg EWMA
            t1 = time.perf_counter()
            router.submit(img).result(timeout=60)
            warm.append(time.perf_counter() - t1)
        warm.sort()
        healthy_p50_s = max(warm[len(warm) // 4], 1e-3)  # lower quartile ~ healthy replicas
        # capped well below capacity: this round measures DETECTION and the
        # tail, not throughput — the round must outlast eject + recovery
        gray_qps = min(max(3.0, 0.4 / healthy_p50_s), 20.0)
        gray["target_qps"] = round(gray_qps, 2)
        s_before = reg.snapshot()
        slow0 = s_before.get("fleet.slow_ejections", 0)
        eject0 = s_before.get("fleet.ejections", 0)
        armed = {}  # set mid-round: the detector's zero point
        eject_at = {}

        def _watch():
            while "t" not in eject_at:
                t_armed = armed.get("t")
                if t_armed is not None and time.perf_counter() - t_armed > 120:
                    return
                if reg.snapshot().get("fleet.slow_ejections", 0) > slow0:
                    eject_at["t"] = time.perf_counter()
                    return
                time.sleep(0.05)

        watcher = threading.Thread(target=_watch, daemon=True)
        watcher.start()
        lat_rows = []
        lat_lock = threading.Lock()
        rs = np.random.RandomState(seed + 9)
        gaps = rs.exponential(1.0 / gray_qps, size=gray_requests)
        pending = []
        t_next = time.perf_counter()
        for i in range(gray_requests):
            t_next += gaps[i]
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if i == gray_requests // 3 and "t" not in armed:
                # arm the detector MID-round: the first third measures the
                # straggler-poisoned tail, then time-to-eject runs from here
                armed["t"] = time.perf_counter()
                router.set_slow_ejection(True)
            t1 = time.perf_counter()
            fut = router.submit(img)

            def _stamp(fut, t1=t1):
                # keyed by SUBMIT time: a request submitted after the
                # ejection can only have been routed to healthy replicas,
                # so the before/after split is routing-honest even for
                # straggler-queued requests completing late
                if fut.exception() is None:
                    with lat_lock:
                        lat_rows.append((t1, time.perf_counter() - t1))

            fut.add_done_callback(_stamp)
            pending.append(fut)
        unresolved = failed = 0
        for fut in pending:
            try:
                fut.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — typed verdicts; hangs counted apart
                from concurrent.futures import TimeoutError as FutTimeout

                if isinstance(e, FutTimeout):
                    unresolved += 1
                else:
                    failed += 1
        watcher.join(timeout=5)
        t_eject = eject_at.get("t")
        t_armed = armed.get("t")
        s_end = reg.snapshot()
        gray.update({
            "submitted": gray_requests,
            "completed": len(lat_rows),
            "failed": failed,
            "unresolved": unresolved,
            "slow_ejections": int(s_end.get("fleet.slow_ejections", 0) - slow0),
            "ejections_total": int(s_end.get("fleet.ejections", 0) - eject0),
            "time_to_eject_s": (round(t_eject - t_armed, 3)
                                if t_eject is not None and t_armed is not None else None),
        })
        if t_eject is not None:
            before = sorted(d for t, d in lat_rows if t <= t_eject)
            after = sorted(d for t, d in lat_rows if t > t_eject)
            gray["p99_ms_before_eject"] = round(_percentile(before, 0.99) * 1e3, 3)
            gray["p99_ms_after_eject"] = round(_percentile(after, 0.99) * 1e3, 3)
            gray["post_eject_samples"] = len(after)
            gray["tail_recovery"] = (
                round(gray["p99_ms_before_eject"] / gray["p99_ms_after_eject"], 3)
                if gray["p99_ms_after_eject"] else None
            )
        out["gray"] = gray
        out["cpu_rehearsal_note"] = _OVERLOAD_CPU_CAVEAT
        return out
    finally:
        router.stop()
        fleet.stop()


_PARTITION_CPU_CAVEAT = (
    "cpu_rehearsal: router, replicas, proxies, and the load generator share "
    "this box's core(s), so absolute latency/QPS are contention-dominated. "
    "The pinned structural claims are host-independent: under each seeded "
    "partition shape injected at the SOCKET level (netchaos proxy) every "
    "submitted request resolves as completed or typed-rejected with zero "
    "failures, the blackholed replica is ejected within the poll-budget "
    "bound (eject_failures x (poll interval + connect budget) + slack) "
    "rather than the read timeout, the healed link readmits after its "
    "probation, and a silently-vanished leased backend is REMOVED within "
    "TTL + one poll sweep. Replica count and absolute rates are a real "
    "multi-host measurement — the same caveat discipline as r02..r08."
)


def _partition_round(router, image, *, n_requests, target_qps, seed,
                     hooks=(), result_timeout_s=60.0):
    """One open-loop Poisson round through the fleet router with indexed
    ``hooks`` [(idx, fn), ...] fired just before their request index (the
    fault-onset / heal injection points). Every future resolves at the end
    — a hang is ``unresolved`` > 0, never a stuck bench; latencies stamp at
    resolution via callbacks."""
    from concurrent.futures import TimeoutError as FutTimeout

    import numpy as np

    from yet_another_mobilenet_series_tpu.serve.client import ClientHTTPError

    rs = np.random.RandomState(seed)
    gaps = rs.exponential(1.0 / target_qps, size=n_requests)
    hooks = sorted(hooks)
    pending = []
    lat = []
    lat_lock = threading.Lock()

    def _stamp(t0):
        def cb(fut):
            if fut.exception() is None:
                with lat_lock:
                    lat.append(time.perf_counter() - t0)
        return cb

    t_start = time.perf_counter()
    t_next = t_start
    hook_i = 0
    for i in range(n_requests):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        while hook_i < len(hooks) and i >= hooks[hook_i][0]:
            hooks[hook_i][1]()
            hook_i += 1
        t0 = time.perf_counter()
        fut = router.submit(image)
        fut.add_done_callback(_stamp(t0))
        pending.append(fut)
    while hook_i < len(hooks):  # a heal indexed past the end still fires
        hooks[hook_i][1]()
        hook_i += 1
    out = {"submitted": n_requests, "completed": 0, "rejected": 0, "failed": 0,
           "unresolved": 0}
    for fut in pending:
        try:
            fut.result(timeout=result_timeout_s)
            out["completed"] += 1
        except FutTimeout:
            out["unresolved"] += 1  # a real hang: the router broke its contract
        except ClientHTTPError as e:
            out["rejected" if e.status < 500 else "failed"] += 1
        except Exception:  # noqa: BLE001 — typed route failure = client-visible
            out["failed"] += 1
    wall = time.perf_counter() - t_start
    lat.sort()
    out.update({
        "wall_s": round(wall, 3),
        "qps": round(out["completed"] / wall, 2) if wall else 0.0,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
    })
    return out


_PARTITION_ROUND_KEYS = ("fleet.route_retries", "fleet.ejections", "fleet.readmissions",
                         "fleet.partition_ejections", "serve.client.connect_timeouts")


def measure_partition(*, replicas, requests, target_qps, seed, poll_interval_s,
                      eject_failures, connect_timeout_s, read_timeout_s,
                      eject_cooldown_s, lease_ttl_s, flap_period_s, flap_down_s):
    """The ``--partition`` measurement (the r09 shape): N in-process echo
    replicas (real Frontend + pipelined batcher over a trivial engine — no
    jax, so the round measures the TRANSPORT, not a model), each behind its
    own seeded netchaos proxy, one fleet router over the proxy addresses.

    Four seeded fault rounds on one schedule family — ``blackhole``,
    ``reset``, ``half_open``, ``flap`` — each injecting its shape at the
    socket level a third of the way in and healing at two thirds, measuring
    DETECTION (fault onset -> ejection, stamped by a counter watcher, never
    by the submit loop), client-visible error rate (the contract is ZERO:
    transport retry absorbs every shape), and RECOVERY (heal -> fully
    routable again, through the post-ejection probation). Then the
    ``membership`` round: a leased replica joins via /register-style
    heartbeats, vanishes silently (heartbeat stops + link blackholed), and
    must be REMOVED by lease expiry within TTL + one poll sweep while
    traffic keeps answering."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.frontend import Frontend
    from yet_another_mobilenet_series_tpu.serve.netchaos import NetChaosProxy
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher
    from yet_another_mobilenet_series_tpu.serve.router import Router

    reg = get_registry()

    class _EchoEngine:
        def predict_async(self, images):
            class _H:
                def result(_self):
                    return images[:, 0, 0, :1].astype(np.float32)

            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    def echo_replica(tag):
        b = PipelinedBatcher(_EchoEngine(), max_batch=8, max_wait_ms=1.0,
                             queue_depth=256, drain_timeout_s=5.0).start()
        fe = Frontend(AdmissionController(b), port=0, replica_id=tag).start()
        return b, fe

    stacks = [echo_replica(f"p{i}") for i in range(replicas)]
    proxies = [NetChaosProxy("127.0.0.1", fe.port, seed=seed + i).start()
               for i, (_, fe) in enumerate(stacks)]
    router = Router(
        [p.addr for p in proxies],
        poll_interval_s=poll_interval_s, eject_failures=eject_failures,
        route_attempts=replicas + 1, client_timeout_s=read_timeout_s,
        connect_timeout_s=connect_timeout_s, eject_cooldown_s=eject_cooldown_s,
        lease_ttl_s=lease_ttl_s, seed=seed,
    ).start()
    poll_read_s = max(connect_timeout_s, 2 * poll_interval_s)
    # the acceptance bound: ejection within the POLL budget (+ slack for a
    # loaded 1-core box), provably far below the read timeout
    detect_bound_s = eject_failures * (poll_interval_s + poll_read_s) + 2.0
    # the fault window must OUTLAST the expected detection (else the heal
    # races the ejection and the round measures nothing), and the round
    # must outlast lead + window + a recovery tail — auto-extend requests
    # so operator-tuned rates cannot produce a degenerate round
    window_s = eject_failures * (poll_interval_s + poll_read_s) + 0.6
    flap_window_s = max(window_s, 2.2 * flap_period_s)
    lead_s, tail_s = 1.0, 2.5
    requests = max(requests, int(target_qps * (lead_s + flap_window_s + tail_s)) + 1)
    out = {
        "replicas": replicas, "seed": seed, "requests_per_round": requests,
        "target_qps": target_qps,
        "config": {
            "poll_interval_s": poll_interval_s, "eject_failures": eject_failures,
            "connect_timeout_s": connect_timeout_s, "read_timeout_s": read_timeout_s,
            "poll_read_s": poll_read_s, "eject_cooldown_s": eject_cooldown_s,
            "lease_ttl_s": lease_ttl_s,
            "flap_period_s": flap_period_s, "flap_down_s": flap_down_s,
        },
        "detect_bound_s": round(detect_bound_s, 3),
    }
    image = np.full((8, 8, 3), 3.0, np.float32)

    def watch_counter(key, baseline, holder, stamp_key, t0, timeout_s=60.0):
        def watch():
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if reg.snapshot().get(key, 0) > baseline:
                    holder[stamp_key] = time.perf_counter() - t0
                    return
                time.sleep(0.02)

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        return t

    def watch_routable(n, holder, stamp_key, t_holder, heal_key, timeout_s=60.0):
        """Stamps recovery: the first instant ALL n replicas are routable
        again AFTER the heal hook has fired (t_holder[heal_key])."""
        def watch():
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                t_heal = t_holder.get(heal_key)
                if t_heal is not None and router.n_routable() >= n:
                    holder[stamp_key] = time.perf_counter() - t_heal
                    return
                time.sleep(0.02)

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        return t

    try:
        # warm: every replica learns its keep-alive path, the router polls
        for _ in range(3 * replicas):
            router.submit(image).result(timeout=30)

        rounds = {}
        shapes = ("blackhole", "reset", "half_open", "flap")
        for r_i, shape in enumerate(shapes):
            victim = proxies[r_i % replicas]
            s0 = reg.snapshot()
            stamps: dict = {}
            rnd_extra: dict = {}
            t_round0 = time.perf_counter()
            this_window = flap_window_s if shape == "flap" else window_s

            def heal(victim=victim, stamps=stamps, t_round0=t_round0):
                stamps["heal_at"] = time.perf_counter() - t_round0
                stamps["_t_heal"] = time.perf_counter()
                victim.clear()

            def inject(shape=shape, victim=victim, stamps=stamps,
                       t_round0=t_round0, s0=s0, heal=heal, this_window=this_window):
                stamps["fault_at"] = time.perf_counter() - t_round0
                stamps["_t_fault"] = time.perf_counter()
                if shape == "flap":
                    victim.set_fault(None, flap_period_s=flap_period_s,
                                     flap_down_s=flap_down_s)
                else:
                    victim.set_fault(shape)
                # detection stamps come from a counter watcher, never from a
                # submit loop that itself blocks on the faulted leg; the
                # heal rides a TIMER sized to the detection budget so it
                # can never race the ejection it is there to measure
                stamps["_watch"] = watch_counter(
                    "fleet.ejections", s0.get("fleet.ejections", 0),
                    stamps, "detection_s", stamps["_t_fault"])
                t = threading.Timer(this_window, heal)
                t.daemon = True
                t.start()
                stamps["_heal_timer"] = t

            recovery_watch = watch_routable(replicas, rnd_extra, "recovery_s",
                                            stamps, "_t_heal")
            rnd = _partition_round(
                router, image, n_requests=requests, target_qps=target_qps,
                seed=seed + 11 * (r_i + 1),
                hooks=[(max(1, int(lead_s * target_qps)), inject)],
            )
            w = stamps.pop("_watch", None)
            if w is not None:
                w.join(timeout=30)
            timer = stamps.pop("_heal_timer", None)
            if timer is not None:
                timer.join(timeout=2 * this_window + 5)
            recovery_watch.join(timeout=60)
            # converge back BEFORE reading the delta: each round's books
            # then include its own readmission instead of bleeding it into
            # the next round's baseline
            deadline = time.monotonic() + 30
            while router.n_routable() < replicas and time.monotonic() < deadline:
                time.sleep(0.05)
            rnd.update(_fleet_registry_delta(reg, s0, _PARTITION_ROUND_KEYS))
            rnd["fault_at_s"] = round(stamps.get("fault_at", 0.0), 3)
            rnd["heal_at_s"] = round(stamps.get("heal_at", 0.0), 3)
            rnd["detection_s"] = (round(stamps["detection_s"], 3)
                                  if "detection_s" in stamps else None)
            rnd["recovery_s"] = (round(rnd_extra["recovery_s"], 3)
                                 if "recovery_s" in rnd_extra else None)
            rnd["routable_after"] = router.n_routable()
            rounds[shape] = rnd
        out["rounds"] = rounds

        # -- membership: a leased replica joins, vanishes, expires out ------
        b_d, fe_d = echo_replica("leased")
        proxy_d = NetChaosProxy("127.0.0.1", fe_d.port, seed=seed + 99).start()
        s0 = reg.snapshot()
        mem: dict = {}
        router.register(*proxy_d.addr, ttl_s=lease_ttl_s, replica_id="leased")
        renewing = threading.Event()
        renewing.set()

        def renew_loop():
            while renewing.is_set():
                try:
                    router.register(*proxy_d.addr, ttl_s=lease_ttl_s)
                except Exception:  # noqa: BLE001 — bench heartbeat best-effort
                    pass
                time.sleep(lease_ttl_s / 3.0)

        renew_thread = threading.Thread(target=renew_loop, daemon=True)
        renew_thread.start()
        deadline = time.monotonic() + 30
        while router.n_routable() < replicas + 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        mem["joined"] = router.n_routable() == replicas + 1
        stamps_m: dict = {}

        def vanish():
            # silently gone: the heartbeat stops AND the link blackholes —
            # nothing will ever refuse a connection or send a FIN. Only the
            # lease can remove this backend.
            stamps_m["_t_vanish"] = time.perf_counter()
            renewing.clear()
            proxy_d.set_fault("blackhole")
            stamps_m["_watch"] = watch_counter(
                "fleet.lease_expirations", s0.get("fleet.lease_expirations", 0),
                stamps_m, "removed_s", stamps_m["_t_vanish"])

        rnd = _partition_round(
            router, image, n_requests=requests, target_qps=target_qps,
            seed=seed + 77, hooks=[(requests // 3, vanish)],
        )
        w = stamps_m.pop("_watch", None)
        if w is not None:
            w.join(timeout=30)
        renew_thread.join(timeout=5)
        rnd.update(_fleet_registry_delta(
            reg, s0, ("fleet.registrations", "fleet.lease_renewals",
                      "fleet.lease_expirations", "fleet.route_retries")))
        rnd["joined"] = mem["joined"]
        rnd["removed_s"] = (round(stamps_m["removed_s"], 3)
                            if "removed_s" in stamps_m else None)
        # removal bound: the TTL plus one jittered poll sweep plus slack
        rnd["removal_bound_s"] = round(lease_ttl_s + 1.2 * poll_interval_s + 2.0, 3)
        rnd["total_after"] = len(router.replicas_state())
        out["membership"] = rnd
        out["cpu_rehearsal_note"] = _PARTITION_CPU_CAVEAT
        return out
    finally:
        router.stop()
        for p in proxies:
            p.stop()
        try:
            proxy_d.stop()
            fe_d.stop()
            b_d.stop()
        except NameError:
            pass
        for b, fe in stacks:
            fe.stop()
            b.stop()


_CHAOS_CLASS_MIX = {"interactive": 0.5, "batch": 0.3, "best_effort": 0.2}


def _chaos_round(engine, image_sizes, *, seed, n_requests, target_qps,
                 deadline_ms_by_class, fault_kwargs=None, max_retries=2):
    """One open-loop Poisson round through batcher + admission control.

    Arrivals are pre-drawn from the seed (both A/B rounds share them), fire
    on schedule regardless of completions, and every request is resolved at
    the end — a hang shows up as ``unresolved`` > 0, never a stuck bench."""
    from concurrent.futures import TimeoutError as FutTimeout

    import numpy as np

    from yet_another_mobilenet_series_tpu.obs.registry import get_registry
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.batcher import DeadlineExceeded, DrainTimeout
    from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    reg = get_registry()
    if fault_kwargs:
        engine = FaultyEngine(engine, **fault_kwargs)
    batcher = PipelinedBatcher(
        engine, max_batch=8, max_wait_ms=5.0, queue_depth=256, drain_timeout_s=60.0
    ).start()
    admission = AdmissionController(
        batcher, max_retries=max_retries, retry_backoff_ms=5.0,
        breaker_threshold=10, breaker_cooldown_s=0.5, seed=seed,
    )
    rs = np.random.RandomState(seed)
    classes, probs = zip(*sorted(_CHAOS_CLASS_MIX.items()))
    draws_cls = [classes[i] for i in rs.choice(len(classes), size=n_requests, p=probs)]
    draws_size = [image_sizes[i] for i in rs.randint(0, len(image_sizes), size=n_requests)]
    gaps = rs.exponential(1.0 / target_qps, size=n_requests)
    images = {s: rs.normal(0, 1, (s, s, 3)).astype("float32") for s in image_sizes}

    stats = {c: {"submitted": 0, "completed": 0, "rejected": 0, "shed": 0, "failed": 0,
                 "latencies": []} for c in classes}
    pending = []
    lat_counts0 = {c: _hist_counts(f"serve.latency_seconds.{c}") for c in classes}
    s0 = reg.snapshot()
    t_start = time.perf_counter()
    t_next = t_start
    for i in range(n_requests):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)  # open loop: the schedule, not completions, paces us
        cls = draws_cls[i]
        stats[cls]["submitted"] += 1
        t0 = time.perf_counter()
        try:
            fut = admission.submit(
                images[draws_size[i]], priority=cls,
                deadline_ms=deadline_ms_by_class.get(cls),
            )
        except Exception:  # noqa: BLE001 — typed arrival rejection (quota/breaker/deadline)
            stats[cls]["rejected"] += 1
            continue
        pending.append((cls, t0, fut))
    unresolved = 0
    for cls, t0, fut in pending:
        try:
            fut.result(timeout=300)
            stats[cls]["completed"] += 1
            stats[cls]["latencies"].append(time.perf_counter() - t0)
        except (DeadlineExceeded, DrainTimeout):
            stats[cls]["shed"] += 1
        except FutTimeout:
            unresolved += 1  # a real hang: the no-client-ever-hangs invariant broke
        except Exception:  # noqa: BLE001 — typed engine failure (injected or real)
            stats[cls]["failed"] += 1
    wall = time.perf_counter() - t_start
    batcher.stop()
    s1 = reg.snapshot()

    def delta(key):
        return s1.get(key, 0) - s0.get(key, 0)

    out = {
        "wall_s": round(wall, 3),
        "qps": round(sum(s["completed"] for s in stats.values()) / wall, 2) if wall else 0.0,
        "unresolved": unresolved,
        "retries": delta("serve.retries"),
        "injected_failures": delta("serve.faults.failures"),
        "injected_delays": delta("serve.faults.delays"),
        "breaker_opens": delta("serve.breaker_opens"),
        "rejected_total": delta("serve.rejected"),
        "rejected_deadline": delta("serve.rejected_deadline"),
        "rejected_class_full": delta("serve.rejected_class_full"),
        "rejected_breaker": delta("serve.rejected_breaker"),
        "rejected_queue_full": delta("serve.rejected_full"),
        "shed_deadline": delta("serve.shed_deadline"),
        "classes": {},
    }
    for cls in classes:
        s = stats[cls]
        lat = sorted(s.pop("latencies"))
        out["classes"][cls] = {
            **s,
            "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
            # the same window's quantiles as the registry's bucketed
            # histograms saw it (admission-side submit->resolution)
            "registry_quantiles": _hist_delta_quantiles(
                f"serve.latency_seconds.{cls}", lat_counts0[cls]),
            "qps": round(s["completed"] / wall, 2) if wall else 0.0,
        }
    return out


def _chaos_ab(engine, image_sizes, direct_rows, *, seed, n_requests, target_qps, fault_rate):
    """Healthy vs fault-injected open-loop rounds (one arrival schedule)."""
    base_size = image_sizes[0]
    t1_s = next(
        (r["p50_ms"] / 1e3 for r in direct_rows if r["batch"] == min(x["batch"] for x in direct_rows)
         and r["image_size"] == base_size),
        0.05,
    ) or 0.05
    if target_qps <= 0:
        # auto: what serial single-image serving would sustain — the batcher
        # absorbs it; the faulty round then shows what the faults cost
        target_qps = max(2.0, 1.0 / t1_s)
    deadline_ms_by_class = {
        "interactive": max(50.0, 40 * t1_s * 1e3),  # tight-ish: sheds under spikes
        "batch": max(500.0, 200 * t1_s * 1e3),
        # best_effort carries no deadline: it sheds via class quota instead
    }
    fault_kwargs = {
        "seed": seed,
        "failure_rate": fault_rate,
        "fail_at": "result",  # the completion edge, where retries must reach
        "latency_s": 3 * t1_s,
        "latency_rate": fault_rate,
    }
    common = dict(seed=seed, n_requests=n_requests, target_qps=target_qps,
                  deadline_ms_by_class=deadline_ms_by_class)
    return {
        "requests": n_requests,
        "target_qps": round(target_qps, 2),
        "seed": seed,
        "class_mix": _CHAOS_CLASS_MIX,
        "deadline_ms": {k: round(v, 1) for k, v in deadline_ms_by_class.items()},
        "fault": {"failure_rate": fault_rate, "latency_ms": round(3 * t1_s * 1e3, 1),
                  "latency_rate": fault_rate, "fail_at": "result"},
        "healthy": _chaos_round(engine, image_sizes, **common),
        "faulty": _chaos_round(engine, image_sizes, fault_kwargs=fault_kwargs, **common),
    }


def measure(arch, image_sizes, buckets, iters, conc_iters, ab_iters, max_inflight, with_bf16,
            chaos_requests=0, chaos_qps=0.0, chaos_fault_rate=0.05, chaos_seed=0,
            fuse_ladder=(), fused_iters=8, structural=False, structural_rounds=3,
            quant=False, quant_iters=5, quant_rounds=3, quant_top1_min=0.9):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.serve.engine import BF16_PARITY_ATOL, InferenceEngine
    from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle, fold_network

    if arch == "tiny":  # contract-test preset: 2 blocks, compiles in seconds
        mc = ModelConfig(arch="mobilenet_v2", num_classes=16, dropout=0.0,
                         block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}, {"t": 2, "c": 16, "n": 1, "s": 2}])
    else:
        mc = ModelConfig(arch=arch)
    base_size = image_sizes[0]
    net = get_model(mc, base_size)
    params, state = net.init(jax.random.PRNGKey(0))
    # non-trivial BN running stats (fresh init is mean=0/var=1): a fold of
    # the identity affine collapses random-init logits to ~1e-11, which
    # would make the bf16-vs-fp32 parity delta degenerate
    leaves, treedef = jax.tree.flatten(state)
    keys = jax.random.split(jax.random.PRNGKey(1), len(leaves))
    state = jax.tree.unflatten(
        treedef,
        [l + 0.1 * jnp.abs(jax.random.normal(k, l.shape)) + 0.01 for l, k in zip(leaves, keys)],
    )
    bundle = InferenceBundle(net=net, params=fold_network(net, params, state), meta={})

    def make_engine(dtype, fuse=(), overlap=False, staging_slots=2, ring_slots=0):
        return InferenceEngine(bundle, buckets=buckets, compute_dtype=dtype,
                               image_size=base_size, image_sizes=image_sizes,
                               fuse_ladder=fuse, overlap_staging=overlap,
                               staging_slots=staging_slots, ring_slots=ring_slots)

    # the baseline engine stays CHAINED (fuse_ladder=()) so direct /
    # concurrent / chaos rows keep their r01-r03 meaning; the fused engine
    # below exists only for the chained-vs-fused A/B
    engine = make_engine("float32")
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0

    rng = np.random.RandomState(0)
    direct_rows = [
        _direct_row(engine, b, s, iters, rng) for s in engine.image_sizes for b in engine.buckets
    ]
    concurrent_rows = [
        _concurrent_row(engine, b, s, conc_iters, max_inflight, rng)
        for s in engine.image_sizes for b in engine.buckets
    ]
    peak_sync = max(r["qps_sync"] for r in concurrent_rows)
    peak_pipe = max(r["qps_pipelined"] for r in concurrent_rows)
    ab = {
        "pipelined_vs_sync": {
            "peak_qps_sync": peak_sync,
            "peak_qps_pipelined": peak_pipe,
            "peak_speedup": round(peak_pipe / peak_sync, 4) if peak_sync else None,
        }
    }
    if with_bf16:
        bf16 = make_engine("bfloat16")
        bf16.warmup()
        bf16_rows = [_direct_row(bf16, b, base_size, ab_iters, rng) for b in bf16.buckets]
        # parity on one fixed batch at the largest bucket: the measured
        # delta every artifact carries, judged against the pinned tolerance
        xp = rng.normal(0, 1, (buckets[-1], base_size, base_size, 3)).astype("float32")
        ref = engine.predict(xp)
        delta = float(np.max(np.abs(bf16.predict(xp) - ref)))
        logit_scale = float(np.mean(np.abs(ref)))
        fp32_by_bucket = {r["batch"]: r["qps"] for r in direct_rows if r["image_size"] == base_size}
        peak_fp32 = max(fp32_by_bucket.values())
        peak_bf16 = max(r["qps"] for r in bf16_rows)
        ab["bf16_vs_fp32"] = {
            "buckets": [
                {"batch": r["batch"], "qps_bf16": r["qps"], "qps_fp32": fp32_by_bucket[r["batch"]]}
                for r in bf16_rows
            ],
            "peak_qps_fp32": peak_fp32,
            "peak_qps_bf16": peak_bf16,
            "peak_speedup": round(peak_bf16 / peak_fp32, 4) if peak_fp32 else None,
            "max_abs_logit_delta": round(delta, 6),
            "mean_abs_logit": round(logit_scale, 6),
            "parity_atol": BF16_PARITY_ATOL,
            "parity_ok": delta <= BF16_PARITY_ATOL,
        }
    if fuse_ladder:
        eng_fused = make_engine("float32", fuse=fuse_ladder)
        eng_fused.warmup()
        ab["fused_vs_chained"] = _fused_ab(engine, eng_fused, base_size, fused_iters, rng)
    if structural:
        ab["structural_sweep"] = _structural_sweep(
            make_engine, base_size, rounds=max(1, structural_rounds),
            conc_iters=conc_iters, max_inflight=max_inflight, staging_slots=2,
            run_max=4, fuse_ladder=fuse_ladder or (2, 4), rng=rng,
        )
    if quant:
        # the pipeline's ImageNet normalization constants: the realistic
        # (nonzero-mean, delta-gated) denorm; the zero-mean bitwise regime
        # is pinned inside the A/B with its own engine pair
        from yet_another_mobilenet_series_tpu.config import DataConfig

        dc = DataConfig()
        ab["quant"] = _quant_ab(
            net, bundle.params, buckets, base_size, max(1, quant_iters),
            max(1, quant_rounds), rng, mean=dc.mean, std=dc.std,
            top1_min=quant_top1_min,
        )
    chaos = None
    if chaos_requests > 0:
        chaos = _chaos_ab(
            engine, list(engine.image_sizes), direct_rows,
            seed=chaos_seed, n_requests=chaos_requests,
            target_qps=chaos_qps, fault_rate=chaos_fault_rate,
        )
    # whole-run quantiles straight from the registry snapshot (the same
    # .p50/.p95/.p99 columns obs_registry.json and /varz carry): every
    # serving histogram that saw data, keyed by registry name
    from yet_another_mobilenet_series_tpu.obs.registry import get_registry

    snap = get_registry().snapshot()
    registry_quantiles = {
        k[: -len(".count")]: {
            "count": snap[k],
            "p50": snap.get(f"{k[:-len('.count')]}.p50", 0.0),
            "p95": snap.get(f"{k[:-len('.count')]}.p95", 0.0),
            "p99": snap.get(f"{k[:-len('.count')]}.p99", 0.0),
        }
        for k in snap
        if k.startswith("serve.") and k.endswith(".count") and snap[k] > 0
    }
    from bench import provenance

    dev = jax.devices()[0]
    out = {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "n_chips": len(jax.devices()),
        # shared bench provenance stamp (bench.py): jax/jaxlib versions +
        # cpu-rehearsal flag, so every serving artifact is attributable
        "provenance": provenance(),
        "warmup_compile_s": round(warmup_s, 2),
        "buckets": direct_rows,
        "concurrent": concurrent_rows,
        "ab": ab,
        "registry_quantiles": registry_quantiles,
        "peak_qps": max([peak_pipe, peak_sync] + [r["qps"] for r in direct_rows]),
    }
    if chaos is not None:
        out["chaos"] = chaos
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default="mobilenet_v3_large")
    ap.add_argument("--image-sizes", default="224", help="comma ladder; first entry is the base size")
    ap.add_argument("--buckets", default="1,8,32")
    ap.add_argument("--iters", type=int, default=10, help="direct-mode timed predicts per bucket")
    ap.add_argument("--concurrent-iters", type=int, default=6,
                    help="concurrent mode drives max(iters*batch, 32) requests per bucket and mode")
    ap.add_argument("--ab-iters", type=int, default=5, help="bf16 direct-mode iters per bucket")
    ap.add_argument("--max-inflight", type=int, default=2,
                    help="pipelined window; 1 = pure double buffering (stage||compute, no "
                         "concurrent executions — best when host and device share cores)")
    ap.add_argument("--no-bf16", action="store_true", help="skip the fp32-vs-bf16 A/B")
    ap.add_argument("--fused", action="store_true",
                    help="run the chained-vs-fused A/B (whole requests of K max-bucket "
                         "chunks; per-chunk dispatch loop vs ONE fused lax.scan dispatch)")
    ap.add_argument("--fuse-ladder", default="2,4",
                    help="chunk-count ladder for the fused engine (serve.fuse_chunks.ladder)")
    ap.add_argument("--fused-iters", type=int, default=8,
                    help="timed whole-request predicts per K and mode in the fused A/B")
    ap.add_argument("--structural", action="store_true",
                    help="run the interleaved structural sweep: sync vs pipelined vs "
                         "fused vs overlapped on a saturated bucket (dispatches-per-"
                         "wakeup + steady-state achieved-FLOPS deltas — the r05 shape)")
    ap.add_argument("--structural-rounds", type=int, default=3,
                    help="interleaved rounds per mode in the structural sweep")
    ap.add_argument("--quant", action="store_true",
                    help="run the quantized-serving A/B: one interleaved f32 / "
                         "uint8-wire / int8 sweep per bucket with per-request "
                         "serve.h2d_bytes + serve.dispatched_bytes registry "
                         "deltas and the parity verdicts (the r07 shape)")
    ap.add_argument("--quant-iters", type=int, default=5,
                    help="timed predicts per bucket, mode, and round in the quant A/B")
    ap.add_argument("--quant-rounds", type=int, default=3,
                    help="interleaved rounds per mode in the quant A/B")
    ap.add_argument("--quant-top1-min", type=float, default=0.9,
                    help="int8 top-1 agreement gate for the bench's random-init "
                         "model (BELOW the 0.98 production default: random-init "
                         "logits are near-ties, the worst case for argmax "
                         "stability — the caveat is recorded in the artifact)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the REPLICA-FLEET measurement instead of the single-"
                         "process suites: N cli/serve.py replica subprocesses behind "
                         "the router tier — hedged-vs-unhedged A/B, kill -9 "
                         "availability round, autoscaler diurnal trace (the r06 shape)")
    ap.add_argument("--fleet-replicas", type=int, default=2,
                    help="initial replica count (the straggler is the highest slot)")
    ap.add_argument("--fleet-requests", type=int, default=40,
                    help="open-loop requests per fleet round (A/B and kill)")
    ap.add_argument("--fleet-qps", type=float, default=0.0,
                    help="open-loop arrival rate; 0 = auto from the measured p50")
    ap.add_argument("--fleet-straggler-ms", type=float, default=400.0,
                    help="injected completion latency on the straggler replica")
    ap.add_argument("--fleet-phase-s", default="5,20,10",
                    help="low,high,trough durations (s) of the autoscaler's diurnal schedule")
    ap.add_argument("--fleet-seed", type=int, default=0)
    ap.add_argument("--zoo", action="store_true",
                    help="run the multi-model ZOO measurement instead of the "
                         "single-process suites: a 2-replica model-sharded "
                         "fleet (slot 0 int8 small tier, slot 1 f32 big "
                         "tier) A/B'd three ways on one seeded trace — "
                         "big-only baseline, sharded 50/50 pins (zero "
                         "misroutes/5xx), and the confidence cascade "
                         "(escalations > 0, bitwise answers, dispatched-"
                         "FLOPs/request strictly below big-only)")
    ap.add_argument("--zoo-requests", type=int, default=48,
                    help="trace length: requests per zoo arm (each arm "
                         "replays the SAME seeded trace)")
    ap.add_argument("--zoo-qps", type=float, default=0.0,
                    help="open-loop arrival rate per arm; 0 = auto from the "
                         "measured small-tier p50")
    ap.add_argument("--zoo-threshold", type=float, default=-1.0,
                    help="cascade escalation threshold on the top-1 softmax "
                         "margin; < 0 = calibrate to the trace's MEDIAN "
                         "reference margin (both outcomes populated)")
    ap.add_argument("--zoo-int8-top1-min", type=float, default=0.5,
                    help="int8 export agreement gate for the small tier "
                         "(random weights/trace: lower than the production "
                         "0.98 default)")
    ap.add_argument("--zoo-seed", type=int, default=0)
    ap.add_argument("--overload", action="store_true",
                    help="run the OVERLOAD measurement instead of the single-"
                         "process suites: brownout-off vs brownout-on on one "
                         "seeded 3x-capacity open-loop storm (in-process), plus "
                         "a gray-failure fleet round measuring time-to-soft-"
                         "eject and tail recovery (the r08 shape)")
    ap.add_argument("--overload-storm-s", type=float, default=5.0,
                    help="duration of each storm arm (requests = rate x duration)")
    ap.add_argument("--overload-multiple", type=float, default=3.0,
                    help="storm arrival rate as a multiple of measured capacity")
    ap.add_argument("--overload-pace-ms", type=float, default=20.0,
                    help="seeded per-dispatch latency floor pacing the engine so "
                         "capacity (and thus the storm) is box-independent")
    ap.add_argument("--overload-replicas", type=int, default=2,
                    help="fleet size for the gray-failure round (straggler is the "
                         "highest slot)")
    ap.add_argument("--overload-gray-requests", type=int, default=60,
                    help="open-loop requests in the gray-failure round")
    ap.add_argument("--overload-straggler-ms", type=float, default=300.0,
                    help="injected completion latency on the gray straggler")
    ap.add_argument("--overload-seed", type=int, default=0)
    ap.add_argument("--partition", action="store_true",
                    help="run the PARTITION measurement instead of the single-"
                         "process suites: in-process echo replicas behind "
                         "netchaos proxies, seeded blackhole/reset/half_open/"
                         "flap rounds measuring detection, client-visible "
                         "error rate (must be zero), and recovery, plus the "
                         "TTL-lease membership round (the r09 shape). No jax.")
    ap.add_argument("--partition-replicas", type=int, default=3)
    ap.add_argument("--partition-requests", type=int, default=120,
                    help="open-loop requests per partition round")
    ap.add_argument("--partition-qps", type=float, default=30.0,
                    help="open-loop arrival rate per partition round")
    ap.add_argument("--partition-poll-s", type=float, default=0.1,
                    help="router health-poll interval for the partition rounds")
    ap.add_argument("--partition-connect-timeout-s", type=float, default=0.4,
                    help="client TCP-handshake budget (also bounds poll reads)")
    ap.add_argument("--partition-read-timeout-s", type=float, default=2.0,
                    help="client read budget (leg timeout) — detection must "
                         "beat this, proving ejection rides the poll budget")
    ap.add_argument("--partition-lease-ttl-s", type=float, default=1.5,
                    help="lease TTL for the membership round")
    ap.add_argument("--partition-seed", type=int, default=0)
    ap.add_argument("--chaos-requests", type=int, default=80,
                    help="open-loop Poisson requests per chaos round (healthy + faulty)")
    ap.add_argument("--chaos-qps", type=float, default=0.0,
                    help="open-loop arrival rate; 0 = auto from the measured single-image p50")
    ap.add_argument("--chaos-fault-rate", type=float, default=0.05,
                    help="injected failure AND latency-spike probability in the faulty round")
    ap.add_argument("--chaos-seed", type=int, default=0,
                    help="seed for arrivals, class/size mix, and the fault schedule")
    ap.add_argument("--no-chaos", action="store_true", help="skip the chaos A/B")
    ap.add_argument("--out", default="", help="also write the JSON artifact here")
    args = ap.parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    image_sizes = tuple(int(s) for s in args.image_sizes.split(","))

    if args.partition:
        # standalone like --fleet/--overload, but jax-free end to end: the
        # replicas are echo frontends, because the measurement is the
        # TRANSPORT (detection/containment/recovery), not a model
        out = {
            "metric": "partition_blackhole_detect_seconds",
            "value": None,
            "unit": "seconds",
            "vs_baseline": None,
            "vs_baseline_note": ("the implicit baseline is the read timeout: without "
                                 "the connect/read split and poll-budget ejection a "
                                 "blackholed replica pins legs for read_timeout_s"),
            "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        try:
            m = measure_partition(
                replicas=max(2, args.partition_replicas),
                requests=max(30, args.partition_requests),
                target_qps=max(5.0, args.partition_qps),
                seed=args.partition_seed,
                poll_interval_s=args.partition_poll_s,
                eject_failures=2,
                connect_timeout_s=args.partition_connect_timeout_s,
                read_timeout_s=args.partition_read_timeout_s,
                eject_cooldown_s=0.3,
                lease_ttl_s=args.partition_lease_ttl_s,
                flap_period_s=1.0,
                flap_down_s=0.5,
            )
            from bench import provenance

            # no backend is ever touched: a loopback rehearsal by
            # construction (the real multi-host run is the ROADMAP rung)
            out.update({"platform": "cpu", "provenance": provenance(cpu_rehearsal=True),
                        "partition": m})
            out["value"] = m["rounds"]["blackhole"]["detection_s"]
        except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
            out["error"] = f"{type(e).__name__}: {e}"
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    if args.overload:
        # standalone like --fleet: the storm arms own their batcher stacks
        # and the gray round owns replica subprocesses
        import shutil
        import tempfile

        out = {
            "metric": f"{args.arch}_overload_interactive_availability",
            "value": None,
            "unit": "completed/submitted",
            "vs_baseline": None,
            "vs_baseline_note": "the A/B is internal: brownout-off is the baseline arm",
            "image_size": image_sizes[0],
            "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        log_root = tempfile.mkdtemp(prefix="serve_bench_overload_")
        try:
            m = measure_overload(
                args.arch, image_sizes[0], buckets,
                storm_s=max(1.0, args.overload_storm_s),
                multiple=max(1.5, args.overload_multiple),
                pace_ms=max(1.0, args.overload_pace_ms),
                seed=args.overload_seed,
                replicas=max(2, args.overload_replicas),
                gray_requests=max(20, args.overload_gray_requests),
                straggler_ms=args.overload_straggler_ms,
                log_root=log_root,
            )
            import jax

            from bench import provenance

            dev = jax.devices()[0]
            out.update({"platform": dev.platform, "device_kind": dev.device_kind,
                        "provenance": provenance(), "overload": m})
            out["value"] = m["storm"]["interactive_availability_on"]
            shutil.rmtree(log_root, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
            out["error"] = f"{type(e).__name__}: {e} (replica logs under {log_root})"
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    if args.zoo:
        # standalone like --fleet: the zoo arms share one model-sharded
        # replica fleet, so the single-process suites would only add
        # redundant compile time to the artifact
        import shutil
        import tempfile

        out = {
            "metric": f"{args.arch}_zoo_cascade_flops_vs_big_only",
            "value": None,
            "unit": "cascade/big_only dispatched-FLOPs per request",
            "vs_baseline": None,
            "vs_baseline_note": ("the A/B is internal: the big-only arm "
                                 "(one-model-per-fleet) is the baseline; "
                                 "value < 1.0 is the cascade's cost win"),
            "image_size": image_sizes[0],
            "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        log_root = tempfile.mkdtemp(prefix="serve_bench_zoo_")
        try:
            m = measure_zoo(
                args.arch, image_sizes[0],
                requests=max(12, args.zoo_requests),
                target_qps=args.zoo_qps,
                seed=args.zoo_seed,
                threshold=args.zoo_threshold,
                int8_top1_min=args.zoo_int8_top1_min,
                log_root=log_root,
            )
            import jax

            from bench import provenance

            dev = jax.devices()[0]
            out.update({"platform": dev.platform, "device_kind": dev.device_kind,
                        "provenance": provenance(), "zoo": m})
            out["value"] = m["cost"]["cascade_vs_big_only"]
            shutil.rmtree(log_root, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
            out["error"] = f"{type(e).__name__}: {e} (replica logs under {log_root})"
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    if args.fleet:
        # the fleet measurement is standalone: replica subprocesses own the
        # engines, so the single-process suites would only add minutes of
        # redundant compile time to the artifact
        import shutil
        import tempfile

        out = {
            "metric": f"{args.arch}_fleet_requests_per_sec",
            "value": None,
            "unit": "requests/sec",
            "vs_baseline": None,
            "vs_baseline_note": "first fleet round; single-replica rows live in BENCH_SERVE_r01..r05",
            "image_size": image_sizes[0],
            "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        log_root = tempfile.mkdtemp(prefix="serve_bench_fleet_")
        try:
            m = measure_fleet(
                args.arch, image_sizes[0], buckets,
                replicas=max(2, args.fleet_replicas),
                requests=max(10, args.fleet_requests),
                target_qps=args.fleet_qps,
                straggler_ms=args.fleet_straggler_ms,
                seed=args.fleet_seed,
                phase_s=tuple(float(s) for s in args.fleet_phase_s.split(",")),
                log_root=log_root,
            )
            import jax

            from bench import provenance

            dev = jax.devices()[0]
            out.update({"platform": dev.platform, "device_kind": dev.device_kind,
                        "provenance": provenance(), "fleet": m})
            out["value"] = m["hedge_ab"]["unhedged"]["qps"]
            shutil.rmtree(log_root, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
            out["error"] = f"{type(e).__name__}: {e} (replica logs under {log_root})"
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    out = {
        "metric": f"{args.arch}_serve_images_per_sec",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "vs_baseline_note": "BENCH_SERVE_r01 predates the concurrent-submit mode; direct rows are comparable",
        "image_size": image_sizes[0],
        "image_sizes": list(image_sizes),
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        m = measure(args.arch, image_sizes, buckets, max(1, args.iters),
                    max(1, args.concurrent_iters), max(1, args.ab_iters),
                    max(1, args.max_inflight), not args.no_bf16,
                    chaos_requests=0 if args.no_chaos else max(1, args.chaos_requests),
                    chaos_qps=args.chaos_qps, chaos_fault_rate=args.chaos_fault_rate,
                    chaos_seed=args.chaos_seed,
                    fuse_ladder=tuple(int(k) for k in args.fuse_ladder.split(",")) if args.fused else (),
                    fused_iters=max(1, args.fused_iters),
                    structural=args.structural,
                    structural_rounds=args.structural_rounds,
                    quant=args.quant, quant_iters=args.quant_iters,
                    quant_rounds=args.quant_rounds,
                    quant_top1_min=args.quant_top1_min)
        out.update(m)
        out["value"] = m["peak_qps"]
    except Exception as e:  # noqa: BLE001 — contract: structured error, exit 0
        out["error"] = f"{type(e).__name__}: {e}"
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
