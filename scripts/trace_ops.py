"""Aggregate device op times from a jax.profiler xplane trace.

The sandbox's tensorboard_plugin_profile can't convert xplane dumps (protobuf
generation mismatch), so this reads the XSpace proto directly and prints the
op-level breakdown the Pallas/optimization decisions need (VERDICT r1 #4).
Works on the train CLI's step-indexed window AND on the serving frontend's
HTTP-triggered capture (``POST /profile/start|stop`` — docs/SERVING.md).

``--check-table LATENCY_TABLE.json`` cross-checks a measured-latency table
(scripts/latency_table.py) against the trace: the table's predicted
per-image block total next to the trace's aggregated op time, so a table
whose provenance doesn't match the traced hardware shows up as a gross
ratio mismatch instead of silently mis-weighting the NAS penalty.

Usage: python scripts/trace_ops.py /path/to/trace_dir [top_n]
           [--check-table LATENCY_TABLE_r01_cpu_rehearsal.json]
(finds the newest */vm.xplane.pb under the dir)
"""

from __future__ import annotations

import collections
import glob
import json
import os
import re
import sys


def op_kind(name: str) -> str:
    """Collapse op numbering: 'fusion.123' -> 'fusion'. ONE definition for
    every backend's aggregation — the TPU and CPU rankings must never
    diverge on the collapse rule."""
    return re.split(r"[.\d]", name, maxsplit=1)[0].lstrip("%")


def load_xspace(root: str):
    """Newest ``*.xplane.pb`` under ``root`` as a parsed XSpace proto;
    returns (xspace, path). Raises FileNotFoundError when none exists."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True), key=os.path.getmtime)
    if not files:
        raise FileNotFoundError(f"no .xplane.pb under {root}")
    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs, files[-1]


def aggregate_device(plane) -> dict | None:
    """Synchronous-op aggregation of one ``/device:TPU*`` plane: total ps,
    per-op and per-kind sums, async DMA windows (overlapping — tracked
    separately, NOT occupancy), and XLA-module execution stats."""
    events_meta = plane.event_metadata
    modules = []
    for line in plane.lines:
        if "XLA Modules" in line.name:
            durs = sorted(ev.duration_ps / 1e9 for ev in line.events)
            if durs:
                modules.append({"line": line.name, "n": len(durs),
                                "total_ms": sum(durs), "durs_ms": durs})
    per_op: collections.Counter = collections.Counter()
    per_cat: collections.Counter = collections.Counter()
    async_cat: collections.Counter = collections.Counter()
    total_ps = 0
    n_events = 0
    for line in plane.lines:
        if "XLA Ops" not in line.name:
            continue
        for ev in line.events:
            meta = events_meta.get(ev.metadata_id)
            name = meta.name if meta else "?"
            kind = op_kind(name)
            dur = ev.duration_ps
            n_events += 1
            if kind.endswith("-start"):
                # async DMA window, overlaps compute: not occupancy —
                # summing these reported 85% 'copy' on a step that is
                # actually reduce-bound
                async_cat[kind] += dur
                continue
            total_ps += dur
            per_op[name] += dur
            per_cat[kind] += dur
    if not per_op:
        return None
    return {"plane": plane.name, "n_events": n_events,
            # all-zero-duration sync events would divide by zero downstream
            "total_ps": max(total_ps, 1),
            "per_op": per_op, "per_cat": per_cat, "async_cat": async_cat,
            "modules": modules}


def aggregate_host(xs) -> dict:
    """XLA-CPU fallback: thunk events on the ``/host:CPU`` client threadpool
    lines (thread-summed host time, not a device timeline — rehearsal sanity
    and rough op ranking only, never TPU decisions). Client line names vary
    by jaxlib vintage — ``XLAEigen``, ``PjRtCpuClient``, ``tf_XLATfrtCpuClient``
    — so anything carrying ``CpuClient`` or ``XLAEigen`` counts; the old
    exact-two-names match silently aggregated ZERO events on jaxlib 0.4.36."""
    per_cat: collections.Counter = collections.Counter()
    n_events = 0
    for plane in xs.planes:
        if plane.name != "/host:CPU":
            continue
        events_meta = plane.event_metadata
        for line in plane.lines:
            if "CpuClient" not in line.name and "XLAEigen" not in line.name:
                continue
            for ev in line.events:
                meta = events_meta.get(ev.metadata_id)
                name = meta.name if meta else "?"
                if name.startswith(("end:", "ThunkExecutor", "ThreadpoolListener")):
                    continue  # paired markers / executor bookkeeping
                if ev.duration_ps <= 0:
                    continue
                per_cat[op_kind(name)] += ev.duration_ps
                n_events += 1
    return {"per_cat": per_cat, "n_events": n_events,
            "total_ps": max(sum(per_cat.values()), 1)}


def table_prediction(table_path: str) -> dict:
    """Predicted per-image latency of a LATENCY_TABLE artifact at full width
    (sum over entries), plus its provenance — the cross-check baseline."""
    with open(table_path) as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    total_s = 0.0
    for e in entries:
        ch = e["alive_channels"]
        lat = e["latency_s"]
        # full-width point: the ladder's largest alive-channel measurement
        total_s += lat[max(range(len(ch)), key=lambda i: ch[i])]
    return {"entries": len(entries), "blocks_total_ms": total_s * 1e3,
            "provenance": doc.get("provenance", {})}


def print_ranked(per_cat: collections.Counter, total_ps: int, top_n: int) -> None:
    for k, v in per_cat.most_common(top_n):
        print(f"  {k:<40} {v/total_ps*100:6.2f}%  {v/1e12*1000:8.3f} ms")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    table_path = ""
    if "--check-table" in argv:
        i = argv.index("--check-table")
        table_path = argv[i + 1]
        del argv[i : i + 2]
    root = argv[0] if argv else "/tmp/tpu_trace"
    top_n = int(argv[1]) if len(argv) > 1 else 40

    xs, path = load_xspace(root)
    measured_ms = None
    printed_any = False
    for plane in xs.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        agg = aggregate_device(plane)
        if agg is None:
            continue
        printed_any = True
        import statistics

        for m in agg["modules"]:
            print(f"-- {m['line']}: {m['n']} module executions, "
                  f"median {statistics.median(m['durs_ms']):.2f} ms, total {m['total_ms']:.2f} ms")
        total_ps = agg["total_ps"]
        measured_ms = total_ps / 1e12 * 1000
        print(f"\n== {agg['plane']}: {agg['n_events']} op events, "
              f"{measured_ms:.2f} ms synchronous device op time")
        print("\n-- by op kind (sync only) --")
        print_ranked(agg["per_cat"], total_ps, 20)
        print("\n-- async DMA windows (overlapping; not occupancy) --")
        for k, v in agg["async_cat"].most_common(5):
            print(f"  {k:<40} {'':8}{v/1e12*1000:10.3f} ms")
        print(f"\n-- top {top_n} individual sync ops --")
        for k, v in agg["per_op"].most_common(top_n):
            print(f"  {k[:98]:<100} {v/total_ps*100:6.2f}%  {v/1e12*1000:8.3f} ms")
    if not printed_any:
        # CPU-backend traces (the watcher's --cpu-rehearsal, the serving
        # frontend's capture on this box) have no /device:TPU plane. The
        # planes list stays in the output so a trace with NO recognizable
        # plane (GPU backend, malformed dump) is still diagnosable, not a
        # silent zero.
        print(f"no /device:TPU plane in {os.path.basename(path)} — "
              f"falling back to HOST-thread XLA-CPU op times "
              f"(thread-summed, CPU backend; not comparable to TPU ranks); "
              f"planes present: {[p.name for p in xs.planes]}")
        host = aggregate_host(xs)
        measured_ms = host["total_ps"] / 1e12 * 1000
        print(f"\n== /host:CPU: {host['n_events']} thunk events, "
              f"{measured_ms:.2f} ms summed host op time")
        print_ranked(host["per_cat"], host["total_ps"], top_n)

    if table_path:
        pred = table_prediction(table_path)
        prov = pred["provenance"]
        print(f"\n-- latency-table cross-check ({os.path.basename(table_path)}) --")
        print(f"  table: {pred['entries']} entries, predicted "
              f"{pred['blocks_total_ms']:.3f} ms/image over all blocks at full width "
              f"(measured on {prov.get('device_kind', '?')}, "
              f"cpu_rehearsal={prov.get('cpu_rehearsal', '?')})")
        if measured_ms is not None:
            print(f"  trace: {measured_ms:.3f} ms aggregated op time "
                  f"(whole window — divide by traced image count before judging)")
        print("  a gross ratio mismatch means the table's provenance does not "
              "match the traced hardware — regenerate before searching on it")
    return 0


if __name__ == "__main__":
    sys.exit(main())
