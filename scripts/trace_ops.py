"""Aggregate device op times from a jax.profiler xplane trace.

The sandbox's tensorboard_plugin_profile can't convert xplane dumps (protobuf
generation mismatch), so this reads the XSpace proto directly and prints the
op-level breakdown the Pallas/optimization decisions need (VERDICT r1 #4).

Usage: python scripts/trace_ops.py /path/to/trace_dir [top_n]
(finds the newest */vm.xplane.pb under the dir)
"""

from __future__ import annotations

import collections
import glob
import os
import re
import sys


def op_kind(name: str) -> str:
    """Collapse op numbering: 'fusion.123' -> 'fusion'. ONE definition for
    every backend's aggregation — the TPU and CPU rankings must never
    diverge on the collapse rule."""
    return re.split(r"[.\d]", name, maxsplit=1)[0].lstrip("%")


def print_ranked(per_cat: collections.Counter, total_ps: int, top_n: int) -> None:
    for k, v in per_cat.most_common(top_n):
        print(f"  {k:<40} {v/total_ps*100:6.2f}%  {v/1e12*1000:8.3f} ms")


def main():
    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_trace"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True), key=os.path.getmtime)
    if not files:
        sys.exit(f"no .xplane.pb under {root}")
    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())

    printed_any = False
    for plane in xs.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        printed_any = True
        events_meta = plane.event_metadata

        for line in plane.lines:
            if "XLA Modules" in line.name:
                durs = sorted(ev.duration_ps / 1e9 for ev in line.events)
                if durs:
                    import statistics

                    print(
                        f"-- {line.name}: {len(durs)} module executions, "
                        f"median {statistics.median(durs):.2f} ms, total {sum(durs):.2f} ms"
                    )

        per_op = collections.Counter()
        per_cat = collections.Counter()
        async_cat = collections.Counter()
        total_ps = 0
        n_events = 0
        for line in plane.lines:
            if "XLA Ops" not in line.name:
                continue
            for ev in line.events:
                meta = events_meta.get(ev.metadata_id)
                name = meta.name if meta else "?"
                kind = op_kind(name)
                dur = ev.duration_ps
                n_events += 1
                if kind.endswith("-start"):
                    # async DMA window, overlaps compute: not occupancy —
                    # summing these reported 85% 'copy' on a step that is
                    # actually reduce-bound
                    async_cat[kind] += dur
                    continue
                total_ps += dur
                per_op[name] += dur
                per_cat[kind] += dur
        if not per_op:
            continue
        # all-zero-duration sync events would divide by zero below
        total_ps = max(total_ps, 1)
        print(f"\n== {plane.name}: {n_events} op events, {total_ps/1e12*1000:.2f} ms synchronous device op time")
        print("\n-- by op kind (sync only) --")
        print_ranked(per_cat, total_ps, 20)
        print("\n-- async DMA windows (overlapping; not occupancy) --")
        for k, v in async_cat.most_common(5):
            print(f"  {k:<40} {'':8}{v/1e12*1000:10.3f} ms")
        print(f"\n-- top {top_n} individual sync ops --")
        for k, v in per_op.most_common(top_n):
            print(f"  {k[:98]:<100} {v/total_ps*100:6.2f}%  {v/1e12*1000:8.3f} ms")
    if not printed_any:
        # CPU-backend traces (the watcher's --cpu-rehearsal) have no
        # /device:TPU plane; XLA-CPU ops run inside Eigen threadpool host
        # lines. Those thunk events DO carry durations, so aggregate them —
        # clearly labeled: thread-summed host time, not a device timeline,
        # and on another backend entirely (useful for rehearsal sanity and
        # rough op ranking only, never for TPU decisions). The planes list
        # stays in the output so a trace with NO recognizable plane (GPU
        # backend, malformed dump) is still diagnosable, not a silent zero.
        print(f"no /device:TPU plane in {os.path.basename(files[-1])} — "
              f"falling back to HOST-thread XLA-CPU op times "
              f"(thread-summed, CPU backend; not comparable to TPU ranks); "
              f"planes present: {[p.name for p in xs.planes]}")
        per_cat = collections.Counter()
        n_events = 0
        for plane in xs.planes:
            if plane.name != "/host:CPU":
                continue
            events_meta = plane.event_metadata
            for line in plane.lines:
                if "XLAEigen" not in line.name and "PjRtCpuClient" not in line.name:
                    continue
                for ev in line.events:
                    meta = events_meta.get(ev.metadata_id)
                    name = meta.name if meta else "?"
                    if name.startswith(("end:", "ThunkExecutor", "ThreadpoolListener")):
                        continue  # paired markers / executor bookkeeping
                    if ev.duration_ps <= 0:
                        continue
                    per_cat[op_kind(name)] += ev.duration_ps
                    n_events += 1
        total_ps = max(sum(per_cat.values()), 1)
        print(f"\n== /host:CPU: {n_events} thunk events, "
              f"{total_ps/1e12*1000:.2f} ms summed host op time")
        print_ranked(per_cat, total_ps, top_n)


if __name__ == "__main__":
    main()
