"""Round-3 TPU session watcher: poll the tunnel; on the first alive window,
run the queued hardware measurements unattended.

The axon tunnel has been dead for every probe this round (~25 min
UNAVAILABLE per attempt; PROFILE.md), but alive windows appear without
warning (round 2 got one). This watcher makes an alive window impossible to
miss: it probes via ``bench.py --probe`` (150 s kill separates alive from
dead), and when the backend comes up it runs, sequentially, ONE job at a
time (never killing a started TPU process — a killed job can wedge the
tunnel for the rest of the session):

  1. scripts/bench_bn.py --out BENCH_BN_r3.json   (the round's A/B)
  2. python bench.py > BENCH_TPU_r3.json          (headline metric)

Before starting a session it waits for any running pytest to finish (this
sandbox has ONE visible core; concurrent CPU load corrupts TPU timings).
Probes continue until the deadline; a SESSION only starts if its full
worst-case budget fits before the deadline, so nothing is mid-flight when
the round's driver wants the chip.

Usage: python scripts/tpu_watch_r3.py [--deadline-min 240] [--interval 60]
Log: stderr (redirect to a file; tail it for status).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, REPO)
from bench import PROBE_TIMEOUT_S, run_probe  # noqa: E402  (the canonical probe: alive/failed/timeout trichotomy)

# Worst-case wall clock of one session attempt: quiet-CPU wait (capped
# below) + re-probe + A/B timeout + headline timeout. PROBES keep running
# until the deadline (cheap, kill-safe); only a SESSION start is gated on
# this budget fitting before the deadline, so nothing is mid-flight when
# the round's driver wants the chip.
QUIET_WAIT_S = 1200
AB_TIMEOUT_S = 3000       # alive-tunnel A/B is ~20 min; 50 min => window died
HEADLINE_TIMEOUT_S = 6000  # above bench.py's own worst case (~4950 s): it
                           # self-bounds via probe/deadline/fallback, so this
                           # backstop should never fire on a live supervisor
SESSION_BUDGET_S = QUIET_WAIT_S + PROBE_TIMEOUT_S + AB_TIMEOUT_S + HEADLINE_TIMEOUT_S

START_TIME = time.time()


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def probe_alive() -> bool:
    status, info = run_probe()
    if status == "alive" and info.get("platform") == "tpu":
        log(f"ALIVE: {info}")
        return True
    log(f"probe status: {status}")
    return False


def wait_for_quiet_cpu(max_wait_s=QUIET_WAIT_S):
    t0 = time.monotonic()
    while time.monotonic() - t0 < max_wait_s:
        r = subprocess.run(["pgrep", "-f", "pytest"], capture_output=True)
        if r.returncode != 0:
            return
        log("pytest running; delaying TPU session for quiet CPU")
        time.sleep(60)
    log("quiet-CPU wait expired; proceeding anyway")


def _fresh_complete_ab(path: str) -> bool:
    if not (os.path.exists(path) and os.path.getmtime(path) >= START_TIME):
        return False
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    return d.get("partial") is False and d.get("platform") == "tpu"


def run_session() -> bool:
    """Returns True only if the round's A/B artifact was actually produced —
    a False lets the caller keep watching for the next alive window."""
    ab_path = os.path.join(REPO, "BENCH_BN_r3.json")
    # a previous session THIS RUN may have secured the A/B — don't spend a
    # fresh (possibly short) alive window redoing it. A pre-existing (stale)
    # artifact from older code must NOT suppress measurement (hence the
    # created-after-watcher-start check), and neither may a PARTIAL one
    # from a mid-sweep crash (bench_bn writes incrementally).
    if _fresh_complete_ab(ab_path):
        log("fresh complete A/B artifact already present; skipping straight to headline")
    else:
        # hitting the A/B timeout means the window closed and the process is
        # stuck in dead-tunnel init — the safe-to-kill probe case, NOT a
        # running TPU job.
        log("session: bench_bn A/B starting")
        try:
            r1 = subprocess.run(
                [sys.executable, os.path.join(REPO, "scripts", "bench_bn.py"), "--out", ab_path],
                cwd=REPO, capture_output=True, text=True, timeout=AB_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            log("bench_bn exceeded its window (closed mid-session?); will keep watching")
            return False
        log(f"bench_bn rc={r1.returncode}; stderr tail: {r1.stderr[-2000:]}")
        # same artifact contract as the skip path: fresh + complete + TPU
        if r1.returncode != 0 or not _fresh_complete_ab(ab_path):
            log("A/B failed or incomplete (window closed?); will keep watching")
            return False
    log("session: headline bench.py starting")
    try:
        # HEADLINE_TIMEOUT_S sits above bench.py's own worst case, so
        # bench.py always exits on its own terms (its internal probe/
        # deadline/fallback logic); this backstop firing would mean a hung
        # supervisor, not a killed mid-run TPU worker
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            cwd=REPO, capture_output=True, text=True, timeout=HEADLINE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        log("bench.py supervisor hung past its own worst case; will rewatch")
        return False
    log(f"bench rc={r2.returncode}; stdout: {r2.stdout[-1000:]}")
    # only a REAL TPU measurement counts as the headline artifact —
    # bench.py prints structured error/fallback JSON on failure too, and
    # recording that would end the watch with a corrupt headline
    headline = None
    for line in reversed(r2.stdout.strip().splitlines()):
        try:
            cand = json.loads(line)
            if isinstance(cand, dict) and "metric" in cand:
                headline = cand
                break
        except json.JSONDecodeError:
            continue
    ok = (
        r2.returncode == 0 and headline is not None
        and headline.get("value") is not None and headline.get("platform") == "tpu"
    )
    if ok:
        with open(os.path.join(REPO, "BENCH_TPU_r3.json"), "w") as f:
            json.dump(headline, f)
            f.write("\n")
        log("session complete")
    else:
        log("headline run produced no TPU measurement; will rewatch")
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=240.0,
                    help="stop starting new probes/sessions after this many minutes")
    ap.add_argument("--interval", type=float, default=60.0, help="sleep between dead probes")
    args = ap.parse_args()
    t_end = time.monotonic() + args.deadline_min * 60
    n = 0
    # probes run until the deadline (cheap, kill-safe); only a SESSION start
    # is gated on the full budget fitting before t_end, so a late-found
    # window is still logged even when there is no time left to use it
    # even a PROBE must fully fit before the deadline: a mid-flight probe at
    # t_end would contend with the round driver's own bench on the tunnel
    while time.monotonic() + PROBE_TIMEOUT_S < t_end:
        n += 1
        log(f"probe #{n}")
        if probe_alive():
            if time.monotonic() + SESSION_BUDGET_S >= t_end:
                log("ALIVE WINDOW FOUND but no time left for a full session before the deadline; exiting")
                return
            wait_for_quiet_cpu()
            # the quiet-CPU wait can outlive an alive window: re-confirm
            # before burning a ~25-min dead-tunnel init inside the session
            if probe_alive() and run_session():
                return
            log("window closed or session failed; resuming watch")
            continue
        log("dead; sleeping")
        time.sleep(args.interval)
    log("deadline reached without an alive window")


if __name__ == "__main__":
    main()
