#!/usr/bin/env bash
# yamt-lint over the package, JSON report, nonzero exit on any finding.
#
# The same check the tier-1 gate runs (tests/test_lint_clean.py), packaged
# for CI / pre-commit: machine-readable output on stdout, findings count on
# stderr. Usage: scripts/lint.sh [extra paths...]
set -euo pipefail

cd "$(dirname "$0")/.."

# the analyzer is pure AST — it never executes package code, so no
# accelerator/platform setup is needed
out=$(python -m yet_another_mobilenet_series_tpu.analysis --format json \
    yet_another_mobilenet_series_tpu/ "$@") || rc=$?
echo "$out"
if [ "${rc:-0}" -ne 0 ]; then
    count=$(echo "$out" | python -c 'import json, sys
try:
    print(json.load(sys.stdin)["count"])
except Exception:
    print("?")')
    echo "yamt-lint: ${count} finding(s) — see docs/LINT.md" >&2
    exit "${rc:-1}"
fi
echo "yamt-lint: clean" >&2
