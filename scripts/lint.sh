#!/usr/bin/env bash
# yamt-lint over the package (all rules) and scripts/ (curated subset),
# nonzero exit on any finding.
#
# The same checks the tier-1 gate runs (tests/test_lint_clean.py), packaged
# for CI / pre-commit: machine-readable output on stdout, findings count on
# stderr. Usage:
#   scripts/lint.sh [--format json|text|github] [extra paths...]
# --format github emits ::error workflow annotations so a GitHub Actions run
# marks the offending lines in the PR diff (analysis/reporters.py).
set -euo pipefail

cd "$(dirname "$0")/.."

FORMAT=json
if [ "${1:-}" = "--format" ]; then
    FORMAT="$2"
    shift 2
fi

# the curated scripts/ subset mirrors tests/test_lint_clean.py SCRIPT_RULES:
# PRNG discipline + version-fragile imports apply to standalone scripts,
# package-convention rules do not
SCRIPT_RULES="YAMT002,YAMT006"

# the analyzer is pure AST — it never executes package code, so no
# accelerator/platform setup is needed
rc=0
out=$(python -m yet_another_mobilenet_series_tpu.analysis --format "$FORMAT" \
    yet_another_mobilenet_series_tpu/ "$@") || rc=$?
echo "$out"
rc2=0
out2=$(python -m yet_another_mobilenet_series_tpu.analysis --format "$FORMAT" \
    --select "$SCRIPT_RULES" scripts/) || rc2=$?
echo "$out2"
if [ "$rc" -ne 0 ] || [ "$rc2" -ne 0 ]; then
    if [ "$FORMAT" = json ]; then
        count=$(printf '%s\n%s\n' "$out" "$out2" \
            | grep -o '"count": [0-9]*' | awk '{s+=$2} END {print s}')
    else
        count="?"
    fi
    echo "yamt-lint: ${count} finding(s) — see docs/LINT.md" >&2
    exit 1
fi
echo "yamt-lint: clean" >&2
