#!/usr/bin/env bash
# yamt-lint over the package (all rules) and scripts/ (curated subset),
# nonzero exit on any finding.
#
# The same checks the tier-1 gate runs (tests/test_lint_clean.py), packaged
# for CI / pre-commit: machine-readable output on stdout, findings count on
# stderr. Usage:
#   scripts/lint.sh [--format json|text|github] [--changed]
#                   [--check-suppressions] [--artifact FILE] [extra paths...]
# --format github emits ::error workflow annotations so a GitHub Actions run
# marks the offending lines in the PR diff (analysis/reporters.py).
# --changed lints only .py files differing from the merge-base with
# ${LINT_BASE:-main} (plus uncommitted and untracked files) — same exit and
# format semantics, for fast pre-commit runs. Interprocedural rules see only
# the changed files in this mode; the tier-1 gate still sweeps everything.
# The whole-package PAIRING rules (YAMT022-025: sent-vs-parsed headers,
# escaping exceptions vs _ERROR_MAP, metric/config drift) are deselected
# here — on a partial file set every contract's other side looks absent and
# they would flood false positives; only the full sweep can judge them.
# --check-suppressions audits suppression comments instead of linting:
# a suppression whose rule no longer fires at its site exits nonzero
# (YAMT900) so stale ones cannot accumulate.
# --artifact FILE (or LINT_ARTIFACT=FILE) additionally writes ONE combined
# machine-readable JSON document — {"package": <report>, "scripts": <report>}
# — to FILE for pre-push hooks / CI upload, regardless of --format; the
# on-stdout format semantics are unchanged.
set -euo pipefail

cd "$(dirname "$0")/.."

FORMAT=json
CHANGED=0
ARTIFACT="${LINT_ARTIFACT:-}"
MODEFLAGS=()
EXTRA=()
while [ $# -gt 0 ]; do
    case "$1" in
        --format) FORMAT="$2"; shift 2 ;;
        --changed) CHANGED=1; shift ;;
        --check-suppressions) MODEFLAGS+=(--check-suppressions); shift ;;
        --artifact) ARTIFACT="$2"; shift 2 ;;
        *) EXTRA+=("$1"); shift ;;
    esac
done

# the curated scripts/ subset mirrors tests/test_lint_clean.py SCRIPT_RULES:
# PRNG discipline + version-fragile imports apply to standalone scripts,
# package-convention rules do not
SCRIPT_RULES="YAMT002,YAMT006"

PKG_PATHS=(yet_another_mobilenet_series_tpu/)
SCRIPT_PATHS=(scripts/)
PKG_DESELECT=()
if [ "$CHANGED" -eq 1 ]; then
    PKG_DESELECT=(--deselect "YAMT022,YAMT023,YAMT024,YAMT025")
    base=$(git merge-base HEAD "${LINT_BASE:-main}" 2>/dev/null || echo HEAD)
    mapfile -t files < <(
        { git diff --name-only "$base" -- '*.py'
          git ls-files --others --exclude-standard -- '*.py'; } | sort -u
    )
    PKG_PATHS=()
    SCRIPT_PATHS=()
    for f in "${files[@]}"; do
        [ -f "$f" ] || continue  # deleted files have nothing to lint
        case "$f" in
            yet_another_mobilenet_series_tpu/*) PKG_PATHS+=("$f") ;;
            scripts/*) SCRIPT_PATHS+=("$f") ;;
        esac
    done
    if [ "${#PKG_PATHS[@]}" -eq 0 ] && [ "${#SCRIPT_PATHS[@]}" -eq 0 ] \
        && [ "${#EXTRA[@]}" -eq 0 ]; then
        echo "yamt-lint: no changed .py files" >&2
        exit 0
    fi
fi

# the analyzer is pure AST — it never executes package code, so no
# accelerator/platform setup is needed
rc=0
out=""
if [ "${#PKG_PATHS[@]}" -gt 0 ] || [ "${#EXTRA[@]}" -gt 0 ]; then
    out=$(python -m yet_another_mobilenet_series_tpu.analysis --format "$FORMAT" \
        ${MODEFLAGS[@]+"${MODEFLAGS[@]}"} ${PKG_DESELECT[@]+"${PKG_DESELECT[@]}"} \
        ${PKG_PATHS[@]+"${PKG_PATHS[@]}"} ${EXTRA[@]+"${EXTRA[@]}"}) || rc=$?
    echo "$out"
fi
rc2=0
out2=""
if [ "${#SCRIPT_PATHS[@]}" -gt 0 ]; then
    out2=$(python -m yet_another_mobilenet_series_tpu.analysis --format "$FORMAT" \
        ${MODEFLAGS[@]+"${MODEFLAGS[@]}"} \
        --select "$SCRIPT_RULES" ${SCRIPT_PATHS[@]+"${SCRIPT_PATHS[@]}"}) || rc2=$?
    echo "$out2"
fi
if [ -n "$ARTIFACT" ]; then
    # one combined JSON document whatever the display format; when stdout is
    # already JSON the reports are reused, otherwise the lint re-runs quietly
    # (pure AST, a few seconds) rather than complicating the display path
    pkg_json="$out"
    scr_json="$out2"
    if [ "$FORMAT" != json ]; then
        pkg_json=""
        scr_json=""
        if [ "${#PKG_PATHS[@]}" -gt 0 ] || [ "${#EXTRA[@]}" -gt 0 ]; then
            pkg_json=$(python -m yet_another_mobilenet_series_tpu.analysis \
                --format json ${MODEFLAGS[@]+"${MODEFLAGS[@]}"} \
                ${PKG_DESELECT[@]+"${PKG_DESELECT[@]}"} \
                ${PKG_PATHS[@]+"${PKG_PATHS[@]}"} ${EXTRA[@]+"${EXTRA[@]}"}) || true
        fi
        if [ "${#SCRIPT_PATHS[@]}" -gt 0 ]; then
            scr_json=$(python -m yet_another_mobilenet_series_tpu.analysis \
                --format json ${MODEFLAGS[@]+"${MODEFLAGS[@]}"} \
                --select "$SCRIPT_RULES" ${SCRIPT_PATHS[@]+"${SCRIPT_PATHS[@]}"}) || true
        fi
    fi
    PKG_JSON="$pkg_json" SCR_JSON="$scr_json" python - "$ARTIFACT" <<'PY'
import json, os, sys

def load(text):
    text = text.strip()
    return json.loads(text) if text else {"count": 0, "findings": []}

doc = {
    "package": load(os.environ.get("PKG_JSON", "")),
    "scripts": load(os.environ.get("SCR_JSON", "")),
}
doc["count"] = doc["package"]["count"] + doc["scripts"]["count"]
with open(sys.argv[1], "w") as fh:
    json.dump(doc, fh, indent=2)
    fh.write("\n")
PY
    echo "yamt-lint: artifact written to ${ARTIFACT}" >&2
fi
if [ "$rc" -ne 0 ] || [ "$rc2" -ne 0 ]; then
    if [ "$FORMAT" = json ]; then
        count=$(printf '%s\n%s\n' "$out" "$out2" \
            | grep -o '"count": [0-9]*' | awk '{s+=$2} END {print s}')
    else
        count="?"
    fi
    echo "yamt-lint: ${count} finding(s) — see docs/LINT.md" >&2
    exit 1
fi
echo "yamt-lint: clean" >&2
