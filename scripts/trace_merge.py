#!/usr/bin/env python
"""Merge one fleet run's per-process Chrome traces into ONE Perfetto file.

A fleet run (cli/fleet.py) writes a router trace at ``<log_dir>/
obs_trace.json`` and one replica trace per slot at ``<log_dir>/r<i>/
obs_trace.json``. Each file's timestamps are µs relative to that PROCESS's
own monotonic origin, so loading them separately tells you nothing about
order across processes. This script joins them on a shared timeline:

- **clock alignment**: every trace carries ``origin_unix`` — the wall clock
  sampled ADJACENT to the monotonic origin its timestamps are relative to
  (obs/trace.py). The earliest origin becomes t=0 and every other process's
  events shift by ``(origin_unix - min_origin_unix) * 1e6`` µs. Alignment
  error is bounded by inter-host wall-clock skew (~NTP, single-digit ms)
  plus the sub-µs adjacent-read gap; on one host it is effectively the
  sub-µs gap. Wall clocks are never differenced WITHIN a process — offsets
  only place whole traces relative to each other (the YAMT017 hazard is
  same-process wall intervals, which stay monotonic).
- **id scoping**: Chrome async ("b"/"e") and flow ("s"/"t"/"f") events
  match on (category, name, id) GLOBALLY — router request #5 and replica
  request #5 would fuse into one bogus row. Every per-process id is
  remapped to ``pid * ID_STRIDE + id``, EXCEPT the cross-process
  ``fleet/leg`` flow events, whose shared id (``trace_id * 16 + seq``,
  serve/context.py) is exactly how the router's per-leg arrow finds the
  replica's ``link_parent`` arrival.
- **pid collisions**: two processes on different hosts can share a pid;
  colliding pids are remapped (the trace's ``process_name`` metadata keeps
  the human label).

The merged doc adds a ``processes`` table (pid, process_name, source file,
applied offset µs) so a reader can audit the alignment. Result: one
hedged request reads as a single waterfall — the router's ``serve/request``
envelope and ``fleet/route`` span on the router lane, a ``fleet/leg`` slice
per leg with flow arrows into BOTH replicas' ``serve/submit`` ->
``serve/request`` envelopes, every replica event carrying the router's
request id in ``args.trace``.

Usage: python scripts/trace_merge.py <log_dir> [-o merged_trace.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# async/flow ids are remapped to pid * ID_STRIDE + id to make them
# process-scoped; tests recover the original via id % ID_STRIDE (request
# ids are process-monotonic counters, far below this)
ID_STRIDE = 1 << 24

# flow names that are cross-process BY DESIGN: their ids must survive the
# merge untouched so the router arrow lands on the replica slice
GLOBAL_FLOW_NAMES = frozenset({"fleet/leg"})


def discover(log_dir: str) -> list[str]:
    """The fleet layout's trace files: the router's at the top, one per
    replica slot under r<i>/ (sorted for deterministic merge order)."""
    paths = []
    top = os.path.join(log_dir, "obs_trace.json")
    if os.path.exists(top):
        paths.append(top)
    paths.extend(sorted(glob.glob(os.path.join(log_dir, "r*", "obs_trace.json"))))
    return paths


def merge(docs: list[dict], sources: list[str] | None = None) -> dict:
    """Merge parsed trace documents (``to_chrome_trace`` output) into one.
    Importable — tests and the bench merge in-memory docs directly."""
    sources = sources or [f"<doc {i}>" for i in range(len(docs))]
    origins = [d.get("origin_unix") for d in docs]
    known = [o for o in origins if isinstance(o, (int, float))]
    base = min(known) if known else 0.0
    merged_events: list[dict] = []
    processes: list[dict] = []
    seen_pids: set[int] = set()
    warnings: list[str] = []
    for doc, origin, src in zip(docs, origins, sources):
        raw_pid = int(doc.get("pid") or 0)
        pid = raw_pid
        while pid in seen_pids:
            pid += ID_STRIDE  # cross-host pid collision: keep lanes separate
        seen_pids.add(pid)
        if isinstance(origin, (int, float)):
            offset_us = (origin - base) * 1e6
        else:
            offset_us = 0.0
            warnings.append(f"{src}: no origin_unix (pre-federation trace?); "
                            f"events left at their own t=0")
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M":
                ev["ts"] = float(ev.get("ts", 0.0)) + offset_us
            if "id" in ev and ev.get("name") not in GLOBAL_FLOW_NAMES:
                # scope per-process ids: async/flow events match on
                # (cat, name, id) across pids, and every process counts
                # its request ids from 1
                ev["id"] = pid * ID_STRIDE + int(ev["id"])
            merged_events.append(ev)
        processes.append({
            "pid": pid,
            "source_pid": raw_pid,
            "process_name": str(doc.get("process_name") or f"pid {raw_pid}"),
            "file": src,
            "offset_us": round(offset_us, 3),
        })
    out = {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "processes": processes,
    }
    if warnings:
        out["warnings"] = warnings
    return out


def merge_files(paths: list[str]) -> dict:
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    return merge(docs, sources=paths)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("log_dir", help="a fleet run's train.log_dir")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default <log_dir>/merged_trace.json)")
    args = ap.parse_args(argv)
    paths = discover(args.log_dir)
    if not paths:
        print(f"trace_merge: no obs_trace.json under {args.log_dir} "
              "(run with obs.trace=true)", file=sys.stderr)
        return 2
    merged = merge_files(paths)
    out_path = args.out or os.path.join(args.log_dir, "merged_trace.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    for p in merged["processes"]:
        print(f"  {p['process_name']:<24} pid {p['pid']:<10} "
              f"offset {p['offset_us'] / 1e3:+.3f} ms  {p['file']}")
    for w in merged.get("warnings", []):
        print(f"  warning: {w}", file=sys.stderr)
    print(f"{len(merged['traceEvents'])} events from {len(paths)} process(es) "
          f"-> {out_path} (open in ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
