#!/usr/bin/env python
"""Dataset prep: ImageFolder tree -> TFRecord shards (reference: the LMDB
build scripts, SURVEY.md §2 #15; TFRecord is the TPU-native storage per the
native-dependency table in SURVEY.md §2).

Writes shards with the standard ImageNet keys (image/encoded JPEG bytes,
image/class/label 1-based) that data/pipeline.py reads.

Usage:
  python scripts/imagefolder_to_tfrecords.py --src /data/imagenet/train \
      --dst /data/tfrecords --split train --shards 1024
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", required=True, help="ImageFolder root (class subdirs)")
    ap.add_argument("--dst", required=True)
    ap.add_argument("--split", default="train")
    ap.add_argument("--shards", type=int, default=128)
    args = ap.parse_args()

    import tensorflow as tf

    from yet_another_mobilenet_series_tpu.data.native_loader import list_image_folder

    paths, labels, classes = list_image_folder(args.src)
    print(f"{len(paths)} images, {len(classes)} classes -> {args.shards} shards")
    os.makedirs(args.dst, exist_ok=True)

    writers = [
        tf.io.TFRecordWriter(os.path.join(args.dst, f"{args.split}-{i:05d}-of-{args.shards:05d}"))
        for i in range(args.shards)
    ]
    for i, (p, l) in enumerate(zip(paths, labels)):
        with open(p, "rb") as f:
            data = f.read()
        ex = tf.train.Example(features=tf.train.Features(feature={
            "image/encoded": tf.train.Feature(bytes_list=tf.train.BytesList(value=[data])),
            # 1-based labels: 0 is the background class in the ImageNet
            # TFRecord convention (data/pipeline.py subtracts 1)
            "image/class/label": tf.train.Feature(int64_list=tf.train.Int64List(value=[l + 1])),
        }))
        writers[i % args.shards].write(ex.SerializeToString())
        if (i + 1) % 10000 == 0:
            print(f"  {i + 1}/{len(paths)}")
    for w in writers:
        w.close()
    with open(os.path.join(args.dst, f"{args.split}-classes.txt"), "w") as f:
        f.write("\n".join(classes))
    print("done")


if __name__ == "__main__":
    main()
