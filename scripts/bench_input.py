#!/usr/bin/env python
"""Input-pipeline throughput benchmark (SURVEY.md §7 hard part 4: host decode
can bottleneck a ≤8h/350-epoch run — 'measure images/sec/chip headroom
early'). Measures images/sec of each available pipeline in isolation (no
device compute), so it can be compared against bench.py's model-step
images/sec/chip to see which side bounds a training run.

Usage:
  python scripts/bench_input.py --pipeline fake                 # tf.data synthetic
  python scripts/bench_input.py --pipeline tfrecord --data-dir /data/tfr
  python scripts/bench_input.py --pipeline native --data-dir /data/imagefolder
Prints one JSON line per measured pipeline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(name: str, it, batch: int, batches: int, warmup: int = 3) -> dict:
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    dt = time.perf_counter() - t0
    out = {"pipeline": name, "images_per_sec": round(batch * batches / dt, 1), "batch": batch, "batches": batches}
    print(json.dumps(out), flush=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pipeline", choices=["fake", "tfrecord", "native"], required=True)
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--batches", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 8)
    ap.add_argument("--deterministic", action="store_true",
                    help="tfrecord only: data.deterministic_input=True (record-exact "
                         "resume via single-stream deterministic interleave) — measures "
                         "the throughput price of the production resume-exactness switch")
    ap.add_argument("--transfer-uint8", action="store_true",
                    help="tfrecord/native: data.transfer_uint8=True (u8 on the wire, "
                         "in-step device normalize) — host-side cost/saving of the "
                         "4x transfer-volume lever")
    args = ap.parse_args()
    if args.deterministic and args.pipeline != "tfrecord":
        ap.error("--deterministic only applies to --pipeline tfrecord")
    if args.transfer_uint8 and args.pipeline == "fake":
        ap.error("--transfer-uint8 needs a real-JPEG pipeline (tfrecord or native)")

    from yet_another_mobilenet_series_tpu.config import DataConfig
    from yet_another_mobilenet_series_tpu.data import make_train_source

    if args.pipeline == "fake":
        cfg = DataConfig(dataset="fake", image_size=args.image_size, fake_num_classes=1000,
                         fake_train_size=max(args.batch * 4, 1024))
    elif args.pipeline == "tfrecord":
        cfg = DataConfig(dataset="imagenet", data_dir=args.data_dir, image_size=args.image_size,
                         decode_threads=args.threads,
                         deterministic_input=args.deterministic,
                         transfer_uint8=args.transfer_uint8)
    else:
        cfg = DataConfig(dataset="folder", loader="native", data_dir=args.data_dir,
                         image_size=args.image_size, decode_threads=args.threads,
                         transfer_uint8=args.transfer_uint8)
    it = make_train_source(cfg, args.batch, seed=0)
    name = (args.pipeline + ("+deterministic" if args.deterministic else "")
            + ("+uint8" if args.transfer_uint8 else ""))
    measure(name, it, args.batch, args.batches)


if __name__ == "__main__":
    main()
