#!/usr/bin/env bash
# Multi-host TPU pod launcher (reference: the multi-node
# torch.distributed.launch + MASTER_ADDR/PORT path, SURVEY.md §2 #15).
#
# Run THIS SAME command on every host of the pod slice (e.g. via
# `gcloud compute tpus tpu-vm ssh $TPU --worker=all --command=...`).
# jax.distributed.initialize() (enabled by dist.multihost=true) discovers the
# coordinator from the TPU metadata — no MASTER_ADDR plumbing needed; that is
# the env:// rendezvous equivalent.
#
# Usage: scripts/train_pod.sh apps/atomnas_c_se.yml [key=value ...]
set -euo pipefail
APP=${1:?usage: train_pod.sh <app.yml> [overrides...]}
shift
exec python -m yet_another_mobilenet_series_tpu.cli.train "app:${APP}" dist.multihost=true "$@"
