#!/usr/bin/env python
"""Training chaos round: corrupt records + an injected NaN step + a SIGTERM
preemption, then a resume — the serve_bench chaos A/B's training twin.

Prints exactly ONE JSON line on stdout in the bench.py artifact shape
(tests/test_bench_contract.py contract: exit 0 always; a failed round emits
``value: null`` with an ``error`` field, never a stack trace) and optionally
writes it via --out. Two rounds, both SUBPROCESSES of cli.train on the tiny
fake-data config so the artifact reflects the real entry point end to end:

1. **chaos round** — ``train.faults`` injects a seeded corrupt-record rate
   (the resilience wrapper must skip and count them), one NaN step (the
   train.guard rollback must skip and count it), and ``kill_at_step`` sends
   the process a real SIGTERM mid-epoch. The process must exit 0 after a
   final SYNCHRONOUS checkpoint, leaving ``preempt_marker.json`` and its
   registry counters in ``obs_registry.json``.
2. **resume round** — the same config with faults off resumes
   (``train.resume`` default) from the marker's step — NOT from zero — and
   trains to completion; the artifact records the killed/resumed steps and
   the loss on both sides of the kill so trajectory continuity is auditable.

The headline ``value`` is the resumed-run step count recovered past the kill
point — > 0 is the survival claim.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _child_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(flags + ["--xla_force_host_platform_device_count=8"])
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    return env


def _base_overrides(log_dir: str) -> list[str]:
    return [
        "data.dataset=fake", "data.image_size=16", "data.fake_train_size=256",
        "data.fake_eval_size=32", "data.fake_num_classes=4",
        "model.arch=mobilenet_v2", "model.num_classes=4", "model.dropout=0.0",
        "model.block_specs=[{t: 2, c: 8, n: 1, s: 2}]",
        "optim.optimizer=sgd", "optim.momentum=0.9", "optim.weight_decay=0.0",
        "schedule.schedule=constant", "schedule.base_lr=0.05",
        "schedule.scale_by_batch=false", "schedule.warmup_epochs=0.0",
        "ema.enable=false",
        "train.batch_size=16", "train.eval_batch_size=16", "train.epochs=2",
        "train.log_every=2", "train.compute_dtype=float32",
        "train.eval_every_epochs=0", "train.checkpoint_every_epochs=1",
        f"train.log_dir={log_dir}",
        "train.guard.enable=true", "train.guard.max_skipped_steps=4",
        "dist.num_devices=8",
    ]


def _run_child(overrides: list[str], timeout_s: float) -> subprocess.CompletedProcess:
    cmd = [sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.train"] + overrides
    return subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s,
                          cwd=REPO, env=_child_env())


def _losses(log_dir: str) -> list[tuple[int, float]]:
    out = []
    try:
        with open(os.path.join(log_dir, "metrics.jsonl")) as f:
            for line in f:
                row = json.loads(line)
                if "train/loss" in row:
                    out.append((int(row["step"]), float(row["train/loss"])))
    except (OSError, ValueError):
        pass
    return out


def _registry(log_dir: str) -> dict:
    try:
        with open(os.path.join(log_dir, "obs_registry.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def run_chaos(log_dir: str, timeout_s: float) -> dict:
    # steps/epoch = 256/16 = 16; kill mid-epoch-1 (the injector indexes
    # PULLS, which lead the step loop by the prefetch depth)
    chaos_over = _base_overrides(log_dir) + [
        "train.faults.enable=true", "train.faults.seed=7",
        "train.faults.corrupt_record_rate=0.08",
        "train.faults.nan_at_steps=[5]",
        "train.faults.kill_at_step=10",
    ]
    proc = _run_child(chaos_over, timeout_s)
    detail: dict = {"exit_code": proc.returncode}
    if proc.returncode != 0:
        raise RuntimeError(
            f"chaos round exited {proc.returncode}: {proc.stderr[-800:]}")
    marker_path = os.path.join(log_dir, "preempt_marker.json")
    if not os.path.exists(marker_path):
        raise RuntimeError("chaos round left no preempt_marker.json "
                           f"(stdout tail: {proc.stdout[-400:]})")
    with open(marker_path) as f:
        marker = json.load(f)
    reg = _registry(log_dir)
    losses = _losses(log_dir)
    detail.update(
        killed_step=int(marker["step"]),
        reason=marker.get("reason"),
        corrupt_records=reg.get("data.corrupt_records", 0),
        injected_corrupt=reg.get("train.faults.corrupt_records", 0),
        injected_nan_steps=reg.get("train.faults.nan_steps", 0),
        skipped_steps=reg.get("train.skipped_steps", 0),
        nonfinite_events=reg.get("train.nonfinite_events", 0),
        preemptions=reg.get("train.preemptions", 0),
        loss_before_kill=losses[-1][1] if losses else None,
        health_abort=os.path.exists(os.path.join(log_dir, "train_health.json")),
    )
    return detail


def run_resume(log_dir: str, killed_step: int, timeout_s: float) -> dict:
    proc = _run_child(_base_overrides(log_dir), timeout_s)
    if proc.returncode != 0:
        raise RuntimeError(
            f"resume round exited {proc.returncode}: {proc.stderr[-800:]}")
    m = re.search(r"resumed at step (\d+)", proc.stdout)
    if not m:
        raise RuntimeError("resume round never resumed "
                           f"(stdout tail: {proc.stdout[-400:]})")
    resumed_step = int(m.group(1))
    losses = _losses(log_dir)
    after = [l for s, l in losses if s > killed_step]
    reg = _registry(log_dir)
    return {
        "exit_code": proc.returncode,
        "resumed_step": resumed_step,
        "marker_consumed": not os.path.exists(os.path.join(log_dir, "preempt_marker.json")),
        "final_step": losses[-1][0] if losses else None,
        "loss_after_resume": after[0] if after else None,
        "final_loss": after[-1] if after else None,
        "restore_fallbacks": reg.get("ckpt.restore_fallbacks", 0),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="")
    ap.add_argument("--log-dir", default="", help="default: a fresh temp dir")
    ap.add_argument("--timeout-s", type=float, default=240.0, help="per child run")
    args = ap.parse_args(argv)

    if args.log_dir:
        log_dir = args.log_dir
        os.makedirs(log_dir, exist_ok=True)
    else:
        import tempfile

        log_dir = tempfile.mkdtemp(prefix="yamt_train_chaos_")

    from bench import provenance

    artifact = {
        "metric": "train_chaos_recovered_steps",
        "value": None,
        "unit": "steps",
        "vs_baseline": None,
        "platform": "cpu",
        "log_dir": log_dir,
        # shared bench provenance stamp (bench.py). cpu_rehearsal is pinned:
        # the children run under JAX_PLATFORMS=cpu and this parent process
        # never imports jax, so the stamp cannot infer it
        "provenance": provenance(cpu_rehearsal=True),
    }
    try:
        chaos = run_chaos(log_dir, args.timeout_s)
        resume = run_resume(log_dir, chaos["killed_step"], args.timeout_s)
        artifact["chaos"] = chaos
        artifact["resume"] = resume
        if resume["final_step"] is not None:
            artifact["value"] = float(resume["final_step"] - resume["resumed_step"])
    except (RuntimeError, subprocess.TimeoutExpired, OSError, ValueError) as e:
        artifact["error"] = f"{type(e).__name__}: {e}"

    line = json.dumps(artifact)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    # match the bench.py contract: a SIGTERM'd driver still gets the artifact
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    sys.exit(main())
