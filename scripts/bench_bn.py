"""A/B the BatchNorm normalize variants (train.bn_mode) on the full
MobileNetV3-L train step — the round-3 attack on the 52% BN-stat-reduction
share of the round-2 TPU trace (PROFILE.md "Where the time goes").

Variants (ops/layers.py BatchNorm.apply):
  exact   — f32 (x - mean)*scale + beta, the reference-parity baseline
  folded  — precomputed f32 per-channel scale/bias, single FMA
  compute — scale/bias cast to the compute dtype, FMA fully in bf16
each optionally under train.remat (activation rematerialization), which
changes what XLA materializes between the forward stat-reduces and the
backward companions.

Measurement methodology (mandatory on the axon tunnel; PROFILE.md):
iterations are naturally chained (TrainState threads through), and every
timed region ends with a device_get of a scalar that depends on the work.
block_until_ready is NOT a barrier here.

Usage: python scripts/bench_bn.py [--batch 256] [--iters 20] [--out FILE]
Prints one JSON line to stdout; table to stderr.

--xla-flags-sweep (VERDICT r3 #7): instead of the variant A/B, re-time ONE
variant (the BENCH_TUNING.json winner, else exact:0) under each entry of a
curated XLA/libtpu flag list, one subprocess per flag set (flags must be in
the env before any backend touch). Generic --xla_* tokens go to XLA_FLAGS;
--xla_tpu_* tokens go to LIBTPU_INIT_ARGS (the host XLA build aborts on
them — bench.partition_flags documents the probe). A flag set the child
aborts on is recorded as an error row, not a sweep failure. NOTE: whether
the axon tunnel propagates LIBTPU_INIT_ARGS to the remote libtpu is
unverified — flat ms_per_step across xla_tpu_* rows would be the tell, and
the artifact keeps per-row numbers so that outcome is self-documenting.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def log(msg):
    print(msg, file=sys.stderr, flush=True)


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Curated single-chip flag sets (PROFILE.md round-3 closing paragraph: the
# post-A/B lever). "" is the mandatory baseline. scoped_vmem sizes the
# fusion vmem budget (larger => bigger fusions around the BN reduces);
# latency_hiding_scheduler=false and rwb_fusion=false toggle the two
# schedule/fusion passes most likely to interact with the reduce-dominated
# profile (PROFILE.md "Where the time goes").
DEFAULT_FLAG_SETS = (
    ";--xla_tpu_scoped_vmem_limit_kib=65536"
    ";--xla_tpu_scoped_vmem_limit_kib=98304"
    ";--xla_tpu_enable_latency_hiding_scheduler=false"
    ";--xla_tpu_rwb_fusion=false"
)


def _variant_token_from_tuning() -> str:
    """BENCH_TUNING.json winner as a --variants token, else the baseline."""
    from bench import TUNING_PATH  # single source for the tuning-file path

    try:
        with open(TUNING_PATH) as f:
            raw = json.load(f)
        mode = raw.get("bn_mode", "exact")
        if raw.get("remat", False):
            remat_tok = "save_conv" if raw.get("remat_policy") == "save_conv" else "full"
        else:
            remat_tok = "0"
        return f"{mode}:{remat_tok}" + (":dot" if raw.get("conv1x1_dot") else "")
    except (OSError, json.JSONDecodeError, AttributeError, TypeError):
        return "exact:0"


def run_sweep(args) -> None:
    """Supervisor for the flag sweep: one child bench_bn per flag set.

    Children time the single tuned variant; rows persist incrementally (a
    mid-sweep tunnel death keeps completed rows — the BENCH_PALLAS_r2
    lesson). This process never touches a backend itself."""
    from bench import apply_flags_env

    token = _variant_token_from_tuning()
    flag_sets = [s.strip() for s in args.flag_sets.split(";")]
    if "" in flag_sets:
        flag_sets.insert(0, flag_sets.pop(flag_sets.index("")))
    else:
        flag_sets.insert(0, "")  # baseline is mandatory: vs_noflags needs it
    log(f"sweep: variant {token!r}, {len(flag_sets)} flag sets")

    rows = []
    def emit(partial: bool):
        base = next((r for r in rows if r["flags"] == "" and "ms_per_step" in r), None)
        for r in rows:
            if base and "ms_per_step" in r:
                r["vs_noflags"] = round(base["ms_per_step"] / r["ms_per_step"], 3)
        out = {
            "bench": "xla_flags_sweep", "variant": token,
            "batch": args.batch, "image_size": args.image_size, "iters": args.iters,
            "flag_sets_completed": len(rows), "flag_sets_planned": len(flag_sets),
            "partial": partial, "rows": rows,
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        return out

    tmp_out = (args.out or os.path.join(REPO, "BENCH_XLA.json")) + ".child"
    for fs in flag_sets:
        try:
            env = apply_flags_env(os.environ.copy(), fs)
        except ValueError as e:  # malformed token: error row, not a sweep abort
            rows.append({"flags": fs, "error": str(e)})
            emit(partial=True)
            continue
        cmd = [sys.executable, os.path.abspath(__file__), "--variants", token,
               "--batch", str(args.batch), "--iters", str(args.iters),
               "--image-size", str(args.image_size), "--out", tmp_out,
               # label the child artifact as what it IS: a flag-set child of
               # the XLA sweep, not a variant A/B — tooling that globs
               # BENCH_*.json must not misparse a leftover intermediate
               # (ADVICE r5 low)
               "--bench-label", "xla_flags_sweep_child"]
        if args.cpu:
            cmd.append("--cpu")
        log(f"sweep: flags {fs!r} starting")
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=args.child_timeout, cwd=REPO, env=env)
        except subprocess.TimeoutExpired:
            # a hung child here means the window died; keep what we have
            rows.append({"flags": fs, "error": f"child timed out after {args.child_timeout}s"})
            emit(partial=True)
            continue
        row = None
        if r.returncode == 0:
            try:
                with open(tmp_out) as f:
                    child = json.load(f)
                if child.get("partial") is False and child["rows"]:
                    c = child["rows"][0]
                    # child batch/image, not the header's request: CPU
                    # children smoke-scale themselves down
                    row = {"flags": fs, "platform": child.get("platform"),
                           "batch": child.get("batch"), "image_size": child.get("image_size"),
                           "ms_per_step": c["ms_per_step"],
                           "img_s_per_chip": c["img_s_per_chip"],
                           "compile_s": c["compile_s"], "loss": c["loss"]}
            except (OSError, json.JSONDecodeError, KeyError, IndexError):
                pass
        if row is None:
            # unknown-flag aborts land here (fast fatal before any backend
            # retry), alongside genuine child failures — keep the evidence
            row = {"flags": fs, "error": f"child rc={r.returncode}: {r.stderr[-300:]}"}
            log(f"sweep: flags {fs!r} FAILED rc={r.returncode}")
        else:
            log(f"sweep: flags {fs!r}: {row['ms_per_step']} ms/step")
        rows.append(row)
        emit(partial=True)
    try:
        os.remove(tmp_out)
    except FileNotFoundError:
        pass
    print(json.dumps(emit(partial=False)), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--out", default="")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the sandbox's sitecustomize "
                         "force-selects the axon TPU platform otherwise, and a "
                         "dead tunnel burns ~25 min in backend init)")
    ap.add_argument("--dispatch-probe", action="store_true",
                    help="after the variants, time the exact/no-remat config "
                         "both as chained per-step dispatches and as ONE "
                         "lax.scan(iters) dispatch; the delta is the per-step "
                         "host-dispatch/tunnel tax the chained methodology "
                         "includes and the MFU math should know about")
    ap.add_argument("--xla-flags-sweep", action="store_true",
                    help="sweep --flag-sets over the BENCH_TUNING.json winner "
                         "(one child process per flag set) instead of the variant A/B")
    ap.add_argument("--flag-sets", default=DEFAULT_FLAG_SETS,
                    help="semicolon-separated flag strings for --xla-flags-sweep; "
                         "'' (the no-flags baseline) is always run first")
    ap.add_argument("--child-timeout", type=int, default=1500,
                    help="per-flag-set child budget in --xla-flags-sweep")
    ap.add_argument("--bench-label", default="bn_mode_train_step_ab",
                    help="'bench' field written into the artifact; the sweep "
                         "supervisor sets xla_flags_sweep_child on its children "
                         "so intermediates can't be mistaken for a variant A/B")
    ap.add_argument(
        "--variants",
        default="exact:0,folded:0,compute:0,fused_vjp:0,sdot:0,compute_sdot:0,exact:full,exact:save_conv,compute:save_conv,exact:0:dot,sdot:0:dot",
        help="comma list of bn_mode:remat[:dot] where remat is 0 (off), "
             "1/full (jax.checkpoint), or save_conv (keep MXU outputs, "
             "recompute BN/act chains); a trailing ':dot' lowers 1x1 convs "
             "as explicit matmuls (train.conv1x1_dot)",
    )
    args = ap.parse_args()

    if args.xla_flags_sweep:
        # supervisor mode: children own every backend touch
        run_sweep(args)
        return

    # all tokens validated before ANY backend touch or variant run — a typo
    # must fail in milliseconds, not after a 25-min dead-tunnel init or
    # mid-sweep in a scarce hardware window
    from yet_another_mobilenet_series_tpu.ops.layers import BN_MODES

    variants = []
    for spec_str in args.variants.split(","):
        parts = spec_str.strip().split(":")
        if len(parts) < 2:
            raise SystemExit(f"malformed variant {spec_str.strip()!r} (expected bn_mode:remat[:dot])")
        mode, remat_s = parts[0], parts[1]
        extra = parts[2:]
        if mode not in BN_MODES:
            raise SystemExit(f"unknown bn_mode token {mode!r} in --variants (valid: {BN_MODES})")
        if remat_s not in ("0", "1", "full", "save_conv"):
            raise SystemExit(f"unknown remat token {remat_s!r} in --variants (use 0, 1, full, or save_conv)")
        if extra not in ([], ["dot"]):
            raise SystemExit(f"unknown trailing token(s) {extra!r} in --variants (only ':dot' is valid)")
        variants.append((mode, remat_s != "0", remat_s if remat_s == "save_conv" else "full", bool(extra)))

    if args.out:
        # writability must fail in milliseconds too, not after the first
        # ~25-min variant ("a": never truncates a previous partial artifact)
        open(args.out, "a").close()

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from yet_another_mobilenet_series_tpu.utils.benchkit import build_train_fixture, sync

    platform = jax.default_backend()
    kind = jax.devices()[0].device_kind
    if platform == "cpu":
        # smoke scale so the script is testable without the tunnel
        args.batch = min(args.batch, 8)
        args.image_size = min(args.image_size, 64)
        args.iters = min(args.iters, 3)
    log(f"bench_bn: {platform} ({kind}), batch {args.batch}, image {args.image_size}, {args.iters} iters")

    key = jax.random.PRNGKey(0)
    rows = []
    def emit(partial: bool):
        """Persist what's measured SO FAR: a mid-sweep tunnel crash must not
        discard completed rows (the BENCH_PALLAS_r2 12-of-15 lesson)."""
        base = next(
            (r for r in rows if r["bn_mode"] == "exact" and r["remat"] == "off" and not r["conv1x1_dot"]),
            None,
        )
        for r in rows:
            if base:
                r["vs_exact"] = round(base["ms_per_step"] / r["ms_per_step"], 3)
        out = {
            "bench": args.bench_label, "platform": platform, "device_kind": kind,
            "batch": args.batch, "image_size": args.image_size, "iters": args.iters,
            "dtype": "bfloat16",
            "variants_completed": len(rows),
            "variants_planned": len(variants) + (1 if args.dispatch_probe else 0),
            "partial": partial,
            "method": "chained train steps, device_get(loss) barrier (PROFILE.md methodology)",
            "rows": rows,
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        return out

    for mode, remat, policy, dot in variants:
        step_fn, ts, b, _ = build_train_fixture(
            args.batch, args.image_size, remat=remat, remat_policy=policy, bn_mode=mode,
            conv1x1_dot=dot,
        )
        t0 = time.perf_counter()
        ts, metrics = step_fn(ts, b, key)
        sync(metrics["loss"])
        compile_s = time.perf_counter() - t0
        for _ in range(3):
            ts, metrics = step_fn(ts, b, key)
        sync(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(args.iters):
            ts, metrics = step_fn(ts, b, key)
        loss = sync(metrics["loss"])
        dt = (time.perf_counter() - t0) / args.iters
        img_s = args.batch / dt
        remat_label = "off" if not remat else policy
        rows.append({
            "bn_mode": mode, "remat": remat_label, "conv1x1_dot": dot,
            "ms_per_step": round(dt * 1e3, 2),
            "img_s_per_chip": round(img_s / len(jax.devices()), 1),
            "compile_s": round(compile_s, 1), "loss": round(loss, 4),
        })
        log(f"  bn_mode={mode:<8} remat={remat_label:<9} dot={int(dot)}: {dt*1e3:8.2f} ms/step, "
            f"{img_s:8.0f} img/s, loss {loss:.4f} (compile {compile_s:.0f}s)")
        if len(rows) < len(variants):
            emit(partial=True)
        # free the variant's buffers before building the next one
        step_fn = ts = b = None

    # secure the complete A/B artifact BEFORE the diagnostic probe: a probe
    # failure (OOM from the un-donated scan state, a dying window) must
    # never void 11 measured variants — the watcher would discard the
    # scarce window and re-run everything
    emit(partial=False)
    if args.dispatch_probe:
        try:
            rows.append(_dispatch_probe(args, build_train_fixture, sync))
        except Exception as e:
            log(f"dispatch probe failed ({type(e).__name__}: {e}); A/B artifact unaffected")

    print(json.dumps(emit(partial=False)), flush=True)


def _dispatch_probe(args, build_train_fixture, sync):
    """One scan-of-steps dispatch vs per-step chained dispatches, same
    exact/no-remat config. The scan number is device-only time; chained −
    scan ≈ the per-step dispatch/tunnel overhead baked into every chained
    measurement (and into the headline MFU denominator). The row's bn_mode
    is deliberately NOT a valid mode token so the watcher's adoption rule
    can never pick it as a winner."""
    import jax
    from jax import lax

    key = jax.random.PRNGKey(0)
    step_fn, ts, b, _ = build_train_fixture(args.batch, args.image_size)

    def scan_n(ts, b, rng):
        def body(carry, _):
            new_ts, metrics = step_fn(carry, b, rng)  # jitted fn inlines under trace
            return new_ts, metrics["loss"]
        return lax.scan(body, ts, None, length=args.iters)

    # scan FIRST: step_fn donates its TrainState argument, so the chained
    # loop must only run once the scan is done with `ts` (scan_jit itself
    # does not donate; the inlined step's donation is ignored under trace)
    scan_jit = jax.jit(scan_n)
    ts2, losses = scan_jit(ts, b, key)  # compile + first scan
    sync(losses[-1])
    t0 = time.perf_counter()
    ts2, losses = scan_jit(ts2, b, key)
    loss = sync(losses[-1])
    ms_scan = (time.perf_counter() - t0) / args.iters * 1e3

    # chained baseline (same methodology as the variant rows, INCLUDING the
    # 3-step warmup — first post-compile steps run slow, and an unwarmed
    # chained number would inflate the dispatch tax the probe exists to
    # measure)
    ts1, metrics = step_fn(ts, b, key)
    sync(metrics["loss"])
    for _ in range(3):
        ts1, metrics = step_fn(ts1, b, key)
    sync(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(args.iters):
        ts1, metrics = step_fn(ts1, b, key)
    sync(metrics["loss"])
    ms_chain = (time.perf_counter() - t0) / args.iters * 1e3
    log(f"  dispatch probe: chained {ms_chain:.2f} ms/step vs scan {ms_scan:.2f} ms/step "
        f"-> {ms_chain - ms_scan:+.2f} ms/step dispatch tax")
    return {
        "bn_mode": f"exact[scan{args.iters}]", "remat": "off", "conv1x1_dot": False,
        "ms_per_step": round(ms_scan, 2), "ms_per_step_chained": round(ms_chain, 2),
        "dispatch_tax_ms": round(ms_chain - ms_scan, 2), "loss": round(loss, 4),
        "img_s_per_chip": round(args.batch / ms_scan * 1e3 / len(jax.devices()), 1),
        "note": "scan row is device-only time; not an adoptable variant",
    }


if __name__ == "__main__":
    main()
