"""A/B the BatchNorm normalize variants (train.bn_mode) on the full
MobileNetV3-L train step — the round-3 attack on the 52% BN-stat-reduction
share of the round-2 TPU trace (PROFILE.md "Where the time goes").

Variants (ops/layers.py BatchNorm.apply):
  exact   — f32 (x - mean)*scale + beta, the reference-parity baseline
  folded  — precomputed f32 per-channel scale/bias, single FMA
  compute — scale/bias cast to the compute dtype, FMA fully in bf16
each optionally under train.remat (activation rematerialization), which
changes what XLA materializes between the forward stat-reduces and the
backward companions.

Measurement methodology (mandatory on the axon tunnel; PROFILE.md):
iterations are naturally chained (TrainState threads through), and every
timed region ends with a device_get of a scalar that depends on the work.
block_until_ready is NOT a barrier here.

Usage: python scripts/bench_bn.py [--batch 256] [--iters 20] [--out FILE]
Prints one JSON line to stdout; table to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--out", default="")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the sandbox's sitecustomize "
                         "force-selects the axon TPU platform otherwise, and a "
                         "dead tunnel burns ~25 min in backend init)")
    ap.add_argument(
        "--variants",
        default="exact:0,folded:0,compute:0,fused_vjp:0,exact:full,exact:save_conv,compute:save_conv,exact:0:dot",
        help="comma list of bn_mode:remat[:dot] where remat is 0 (off), "
             "1/full (jax.checkpoint), or save_conv (keep MXU outputs, "
             "recompute BN/act chains); a trailing ':dot' lowers 1x1 convs "
             "as explicit matmuls (train.conv1x1_dot)",
    )
    args = ap.parse_args()

    # all tokens validated before ANY backend touch or variant run — a typo
    # must fail in milliseconds, not after a 25-min dead-tunnel init or
    # mid-sweep in a scarce hardware window
    from yet_another_mobilenet_series_tpu.ops.layers import BN_MODES

    variants = []
    for spec_str in args.variants.split(","):
        parts = spec_str.strip().split(":")
        if len(parts) < 2:
            raise SystemExit(f"malformed variant {spec_str.strip()!r} (expected bn_mode:remat[:dot])")
        mode, remat_s = parts[0], parts[1]
        extra = parts[2:]
        if mode not in BN_MODES:
            raise SystemExit(f"unknown bn_mode token {mode!r} in --variants (valid: {BN_MODES})")
        if remat_s not in ("0", "1", "full", "save_conv"):
            raise SystemExit(f"unknown remat token {remat_s!r} in --variants (use 0, 1, full, or save_conv)")
        if extra not in ([], ["dot"]):
            raise SystemExit(f"unknown trailing token(s) {extra!r} in --variants (only ':dot' is valid)")
        variants.append((mode, remat_s != "0", remat_s if remat_s == "save_conv" else "full", bool(extra)))

    if args.out:
        # writability must fail in milliseconds too, not after the first
        # ~25-min variant ("a": never truncates a previous partial artifact)
        open(args.out, "a").close()

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from yet_another_mobilenet_series_tpu.utils.benchkit import build_train_fixture, sync

    platform = jax.default_backend()
    kind = jax.devices()[0].device_kind
    if platform == "cpu":
        # smoke scale so the script is testable without the tunnel
        args.batch = min(args.batch, 8)
        args.image_size = min(args.image_size, 64)
        args.iters = min(args.iters, 3)
    log(f"bench_bn: {platform} ({kind}), batch {args.batch}, image {args.image_size}, {args.iters} iters")

    key = jax.random.PRNGKey(0)
    rows = []
    def emit(partial: bool):
        """Persist what's measured SO FAR: a mid-sweep tunnel crash must not
        discard completed rows (the BENCH_PALLAS_r2 12-of-15 lesson)."""
        base = next(
            (r for r in rows if r["bn_mode"] == "exact" and r["remat"] == "off" and not r["conv1x1_dot"]),
            None,
        )
        for r in rows:
            if base:
                r["vs_exact"] = round(base["ms_per_step"] / r["ms_per_step"], 3)
        out = {
            "bench": "bn_mode_train_step_ab", "platform": platform, "device_kind": kind,
            "batch": args.batch, "image_size": args.image_size, "iters": args.iters,
            "dtype": "bfloat16",
            "variants_completed": len(rows), "variants_planned": len(variants),
            "partial": partial,
            "method": "chained train steps, device_get(loss) barrier (PROFILE.md methodology)",
            "rows": rows,
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        return out

    for mode, remat, policy, dot in variants:
        step_fn, ts, b, _ = build_train_fixture(
            args.batch, args.image_size, remat=remat, remat_policy=policy, bn_mode=mode,
            conv1x1_dot=dot,
        )
        t0 = time.perf_counter()
        ts, metrics = step_fn(ts, b, key)
        sync(metrics["loss"])
        compile_s = time.perf_counter() - t0
        for _ in range(3):
            ts, metrics = step_fn(ts, b, key)
        sync(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(args.iters):
            ts, metrics = step_fn(ts, b, key)
        loss = sync(metrics["loss"])
        dt = (time.perf_counter() - t0) / args.iters
        img_s = args.batch / dt
        remat_label = "off" if not remat else policy
        rows.append({
            "bn_mode": mode, "remat": remat_label, "conv1x1_dot": dot,
            "ms_per_step": round(dt * 1e3, 2),
            "img_s_per_chip": round(img_s / len(jax.devices()), 1),
            "compile_s": round(compile_s, 1), "loss": round(loss, 4),
        })
        log(f"  bn_mode={mode:<8} remat={remat_label:<9} dot={int(dot)}: {dt*1e3:8.2f} ms/step, "
            f"{img_s:8.0f} img/s, loss {loss:.4f} (compile {compile_s:.0f}s)")
        if len(rows) < len(variants):
            emit(partial=True)
        # free the variant's buffers before building the next one
        step_fn = ts = b = None

    print(json.dumps(emit(partial=False)), flush=True)


if __name__ == "__main__":
    main()
