"""A/B the Pallas fused-depthwise kernel against its XLA lowering on TPU.

This is the harness that produced the round-2 verdict recorded in
ops/pallas_kernels.py and PROFILE.md (kernel loses ~10x end-to-end; not
wired into the model). It stays runnable for future chips/toolchains.

Measurement notes, learned the hard way on the axon tunnel:
- ``jax.block_until_ready`` is NOT a reliable barrier here (it often returns
  at dispatch-acknowledge, yielding physically impossible rates, e.g. >100%
  implied MFU). Every timing below chains each iteration's output into the
  next iteration's input and ends with a device_get of a dependent scalar —
  the only sync the tunnel respects.
- Per-dispatch overhead is ~20 us; single-op timings below a few hundred us
  are floor-dominated, so shapes are timed as a chained loop inside one jit.

Usage: python scripts/bench_pallas.py [--batch 128] [--dtype bfloat16]
Prints one JSON line per measurement to stdout, a table to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def sync(arr) -> float:
    """device_get of a dependent scalar — see module docstring."""
    return float(np.asarray(jax.device_get(arr)).ravel()[0])


def dw_shapes(net, image_size):
    """(hw_in, channels, k, stride, act) for every dw branch, tracking spatial."""
    hw = (image_size - 1) // net.stem.stride + 1
    shapes = []
    for blk in net.blocks:
        for k, g in zip(blk.kernel_sizes, blk.group_channels or (blk.expanded_channels,)):
            shapes.append((hw, g, k, blk.stride, blk.active_fn))
        hw = (hw - 1) // blk.stride + 1
    return shapes


def time_chained(step, x0, iters=10, warmup=2):
    """step(x) -> x' (same shape). Chained => serialized and cache-proof."""
    x = x0
    for _ in range(warmup):
        x = step(x)
    sync(x[(0,) * x.ndim])
    x = x0
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    sync(x[(0,) * x.ndim])
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--inner", type=int, default=8, help="chained kernel calls per jit")
    args = ap.parse_args()

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.ops import pallas_kernels as pk

    platform = jax.default_backend()
    kind = jax.devices()[0].device_kind
    dtype = jnp.dtype(args.dtype)
    B = args.batch
    log(f"bench_pallas: {platform} ({kind}), batch {B}, {args.dtype}, {args.inner} chained calls/step")

    net = get_model(ModelConfig(arch="mobilenet_v3_large"), 224)
    rng = np.random.RandomState(0)

    rows = []
    tot_pallas = tot_xla = 0.0
    for hw, c, k, s, act in dw_shapes(net, 224):
        x0 = jnp.asarray(rng.normal(0, 1, (B, hw, hw, c)), dtype)
        w = jnp.asarray(rng.normal(0, 0.1, (k, k, c)), dtype)
        scale = jnp.asarray(rng.uniform(0.5, 1.5, (c,)), jnp.float32)
        shift = jnp.asarray(rng.normal(0, 0.1, (c,)), jnp.float32)
        mask = jnp.ones((c,), jnp.float32)

        def make_step(fn):
            @jax.jit
            def step(x):
                for _ in range(args.inner):
                    y = fn(x)
                    # fold the (possibly strided-down) output back into the
                    # input so successive calls depend on each other
                    x = x + jnp.mean(y).astype(x.dtype) * 1e-20
                return x

            return step

        t_p = time_chained(
            make_step(lambda x: pk._fused_dw_fwd(x, w, scale, shift, mask, stride=s, act=act)),
            x0, iters=args.iters,
        ) / args.inner
        t_x = time_chained(
            make_step(lambda x: pk._reference_fwd(x, w, scale, shift, mask, stride=s, act=act).astype(dtype)),
            x0, iters=args.iters,
        ) / args.inner
        tot_pallas += t_p
        tot_xla += t_x
        rows.append({"hw": hw, "c": c, "k": k, "s": s, "pallas_us": round(t_p * 1e6, 1), "xla_us": round(t_x * 1e6, 1), "speedup": round(t_x / t_p, 2)})
        log(f"  {hw:4d}x{hw:<4d} c={c:<4d} k={k} s={s}: pallas {t_p*1e6:8.1f}us  xla {t_x*1e6:8.1f}us  x{t_x/t_p:.2f}")

    log(f"  TOTAL dw chain: pallas {tot_pallas*1e3:.2f}ms  xla {tot_xla*1e3:.2f}ms  x{tot_xla/tot_pallas:.2f}")
    print(json.dumps({
        "bench": "pallas_dw_chained", "platform": platform, "device_kind": kind,
        "batch": B, "dtype": args.dtype,
        "total_pallas_ms": round(tot_pallas * 1e3, 3), "total_xla_ms": round(tot_xla * 1e3, 3),
        "xla_over_pallas": round(tot_xla / tot_pallas, 3), "per_shape": rows,
    }), flush=True)


if __name__ == "__main__":
    main()
